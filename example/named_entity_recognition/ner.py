#!/usr/bin/env python
"""Named-entity recognition as sequence tagging (reference
example/named_entity_recognition/src/ner.py — embed tokens, recurrent
encoder, per-token entity classifier over BIO-style tags).

The synthetic corpus embeds 'entity' phrases in noise: an entity is a
reserved trigger token, 1-3 payload tokens, and a reserved end token;
tags follow the BIO scheme (O / B-ENT / I-ENT, with I running through
the end token). The tagger must carry "inside an entity" state from the
trigger until the end marker — left-context structure only a recurrent
tagger can express, and fully predictable from the input (so accuracy
is capped by capacity, not label noise). Scored by entity-token F1, the
NER literature's metric.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

VOCAB = 64
TRIGGER = 1            # token id that starts an entity
ENDTOK = 0             # token id that closes an entity
N_TAGS = 3             # O, B-ENT, I-ENT
O, B, I = 0, 1, 2


def make_data(rng, n, seq_len):
    X = rng.randint(2, VOCAB, (n, seq_len))
    Y = np.zeros((n, seq_len), np.int64)
    for s in range(n):
        pos = 0
        while pos < seq_len - 5:
            if rng.rand() < 0.15:
                k = rng.randint(1, 4)          # payload length
                X[s, pos] = TRIGGER
                Y[s, pos] = B
                Y[s, pos + 1:pos + 1 + k] = I  # payload
                X[s, pos + 1 + k] = ENDTOK
                Y[s, pos + 1 + k] = I          # end marker closes it
                pos += k + 3
            else:
                pos += 1
    return X.astype(np.float32), Y.astype(np.float32)


def f1(pred, true):
    tp = np.logical_and(pred != O, pred == true).sum()
    fp = np.logical_and(pred != O, pred != true).sum()
    fn = np.logical_and(true != O, pred != true).sum()
    p = tp / (tp + fp + 1e-9)
    r = tp / (tp + fn + 1e-9)
    return 2 * p * r / (p + r + 1e-9)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=48)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-f1", type=float, default=0.9)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    Xtr, Ytr = make_data(rng, 512, args.seq_len)
    Xte, Yte = make_data(rng, 128, args.seq_len)

    class Tagger(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.Embedding(VOCAB, 24)
                self.lstm = gluon.rnn.LSTM(args.hidden, layout="NTC")
                self.out = gluon.nn.Dense(N_TAGS, flatten=False)

        def hybrid_forward(self, F, x):
            return self.out(self.lstm(self.embed(x)))   # (B, T, tags)

    net = Tagger()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(Xtr)
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            with autograd.record():
                logits = net(x).reshape((-1, N_TAGS))
                loss = sce(logits, y.reshape((-1,))).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print(f"epoch {epoch} tag loss {tot / (n // args.batch_size):.4f}")

    pred = net(nd.array(Xte)).asnumpy().argmax(-1)
    score = f1(pred, Yte)
    print(f"entity-token F1: {score:.3f}")
    assert score >= args.min_f1, score
    print("NER_OK")


if __name__ == "__main__":
    main()
