#!/usr/bin/env python
"""Variational autoencoder (reference example/vae-gan/ + the Gluon VAE
tutorial — encoder emits (mu, logvar), latent sampled with the
reparameterization trick, loss = reconstruction + KL(q||N(0,1))).

Trained on synthetic two-mode glyph images. Checks the two properties a
working VAE must show: the ELBO improves substantially, and latent-space
DECODING of fresh N(0,1) samples produces images closer to the data
manifold than noise (mean nearest-glyph distance drops vs an untrained
decoder)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

IMG = 16
LATENT = 8


def make_data(rng, glyphs, n):
    y = rng.randint(0, len(glyphs), n)
    X = glyphs[y] + 0.1 * rng.randn(n, IMG * IMG).astype(np.float32)
    return np.clip(X, 0, 1).astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    glyphs = (rng.rand(6, IMG * IMG) > 0.5).astype(np.float32)
    Xtr = make_data(rng, glyphs, 1024)

    class VAE(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.enc = gluon.nn.HybridSequential()
                self.enc.add(gluon.nn.Dense(128, activation="relu"))
                self.mu = gluon.nn.Dense(LATENT)
                self.logvar = gluon.nn.Dense(LATENT)
                self.dec = gluon.nn.HybridSequential()
                self.dec.add(gluon.nn.Dense(128, activation="relu"),
                             gluon.nn.Dense(IMG * IMG, activation="sigmoid"))

        def encode(self, x):
            h = self.enc(x)
            return self.mu(h), self.logvar(h)

        def decode(self, z):
            return self.dec(z)

    net = VAE()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def elbo_loss(x):
        mu, logvar = net.encode(x)
        # reparameterization: z = mu + sigma * eps keeps the sample
        # differentiable wrt the encoder
        eps = nd.random.normal(shape=mu.shape)
        z = mu + nd.exp(0.5 * logvar) * eps
        recon = net.decode(z)
        l_rec = nd.sum((recon - x) ** 2, axis=1)
        l_kl = -0.5 * nd.sum(1 + logvar - mu ** 2 - nd.exp(logvar), axis=1)
        return (l_rec + l_kl).mean()

    def sample_quality(n=64):
        """Mean distance of decoded N(0,1) samples to the nearest glyph."""
        z = nd.array(np.random.RandomState(1).randn(n, LATENT)
                     .astype(np.float32))
        dec = net.decode(z).asnumpy()
        d = np.linalg.norm(dec[:, None, :] - glyphs[None], axis=2)
        return float(d.min(axis=1).mean())

    q0 = sample_quality()
    n = len(Xtr)
    first = last = None
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot, nb = 0.0, 0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            x = nd.array(Xtr[perm[s:s + args.batch_size]])
            with autograd.record():
                loss = elbo_loss(x)
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy()); nb += 1
        avg = tot / nb
        first = first if first is not None else avg
        last = avg
        print(f"epoch {epoch} -ELBO {avg:.2f}")

    q1 = sample_quality()
    print(f"-ELBO first {first:.2f} last {last:.2f}; "
          f"decoded-sample glyph distance {q0:.2f} -> {q1:.2f}")
    assert last < first * 0.5, (first, last)
    assert q1 < q0 * 0.8, (q0, q1)
    print("VAE_OK")


if __name__ == "__main__":
    main()
