#!/usr/bin/env python
"""Neural style transfer (reference example/neural-style/nstyle.py —
Gatys et al.: optimize the INPUT IMAGE so its deep features match a
content image while its feature Gram matrices match a style image).

The reference extracts features with pretrained VGG-19; in this
zero-download setting the extractor is a small fixed random conv net —
random convolutional features still define meaningful content/texture
statistics (Ulyanov et al.'s random-feature ablation), which is enough to
demonstrate the optimization loop: autograd THROUGH the frozen network
INTO the image, Adam on pixels, content + Gram style losses both driven
down together.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def content_image(size):
    """A smooth gradient scene with a bright square (the 'content')."""
    g = np.linspace(0, 1, size, dtype=np.float32)
    img = np.stack([np.tile(g, (size, 1)),
                    np.tile(g[:, None], (1, size)),
                    0.5 * np.ones((size, size), np.float32)])
    q = size // 4
    img[:, q:2 * q, q:2 * q] = 0.9
    return img[None]


def style_image(size):
    """Diagonal stripes — a pure texture (the 'style')."""
    ii, jj = np.meshgrid(np.arange(size), np.arange(size), indexing="ij")
    stripes = (((ii + jj) // 4) % 2).astype(np.float32)
    return np.stack([stripes, 1 - stripes,
                     0.5 * np.ones((size, size), np.float32)])[None]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--style-weight", type=float, default=500.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    class FeatureNet(gluon.nn.HybridBlock):
        """Frozen random extractor; returns per-layer feature maps."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.c1 = gluon.nn.Conv2D(16, 3, padding=1, activation="relu")
                self.c2 = gluon.nn.Conv2D(32, 3, strides=2, padding=1,
                                          activation="relu")
                self.c3 = gluon.nn.Conv2D(64, 3, strides=2, padding=1,
                                          activation="relu")

        def hybrid_forward(self, F, x):
            f1 = self.c1(x)
            f2 = self.c2(f1)
            f3 = self.c3(f2)
            return f1, f2, f3

    def gram(feat):
        b, c, h, w = feat.shape
        f = feat.reshape((c, h * w))
        return mx.nd.dot(f, f, transpose_b=True) / (c * h * w)

    mx.random.seed(args.seed)
    net = FeatureNet()
    net.initialize(mx.init.Xavier())

    content = nd.array(content_image(args.size))
    style = nd.array(style_image(args.size))
    c_feats = net(content)
    s_grams = [gram(f) for f in net(style)]

    img = content.copy()                    # init at content (standard)
    img.attach_grad()
    # hand-rolled Adam on the IMAGE (the 'parameter' here is the picture,
    # not the network — Trainer manages Blocks, so the pixel optimizer is
    # explicit, matching the reference's own custom Adam loop in nstyle.py)
    m = nd.zeros(img.shape)
    v = nd.zeros(img.shape)
    beta1, beta2, eps = 0.9, 0.999, 1e-8

    first = last = None
    for it in range(args.steps):
        with autograd.record():
            feats = net(img)
            l_content = ((feats[2] - c_feats[2]) ** 2).mean()
            l_style = sum(((gram(f) - g) ** 2).mean()
                          for f, g in zip(feats, s_grams))
            loss = l_content + args.style_weight * l_style
        loss.backward()
        t = it + 1
        m = beta1 * m + (1 - beta1) * img.grad
        v = beta2 * v + (1 - beta2) * img.grad ** 2
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
        img = (img - args.lr * mhat / (nd.sqrt(vhat) + eps)).clip(0, 1)
        img.attach_grad()
        val = float(loss.asnumpy())
        if first is None:
            first = val
        last = val
        if it % 10 == 0:
            print(f"step {it:3d} loss {val:.5f} (content {float(l_content.asnumpy()):.5f} "
                  f"style {float(l_style.asnumpy()):.5f})")

    print(f"loss first {first:.5f} last {last:.5f}")
    assert last < first * 0.5, (first, last)
    out = img.asnumpy()
    assert out.min() >= 0.0 and out.max() <= 1.0
    print("NEURAL_STYLE_OK")


if __name__ == "__main__":
    main()
