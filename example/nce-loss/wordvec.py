#!/usr/bin/env python
"""Word embeddings via noise-contrastive estimation (reference
example/nce-loss/wordvec.py + nce.py — word2vec trained with NCE instead
of a full-vocabulary softmax).

Skip-gram with k negative samples per true (center, context) pair: the
binary classifier score(w_c, w_o) = in_embed[w_c] . out_embed[w_o] + b
must rank observed pairs above unigram-noise pairs — the full softmax
never materializes (the whole point of NCE at large vocab). The synthetic
corpus interleaves topic blocks, so words of one topic co-occur and their
learned vectors must cluster.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_corpus(rng, n_topics, words_per_topic, length):
    """Token stream of topic blocks: each block samples from ONE topic's
    word set, so intra-topic co-occurrence dominates."""
    stream = []
    while len(stream) < length:
        t = rng.randint(n_topics)
        block = rng.randint(t * words_per_topic, (t + 1) * words_per_topic,
                            rng.randint(8, 16))
        stream.extend(block.tolist())
    return np.array(stream[:length], np.int64)


def make_pairs(rng, corpus, window, vocab, k_neg, n_pairs):
    """(center, target, label) triples: one true context + k noise words
    drawn from the unigram distribution (here uniform)."""
    centers = np.zeros((n_pairs, 1 + k_neg), np.float32)
    targets = np.zeros((n_pairs, 1 + k_neg), np.float32)
    labels = np.zeros((n_pairs, 1 + k_neg), np.float32)
    for i in range(n_pairs):
        c = rng.randint(window, len(corpus) - window)
        off = rng.randint(1, window + 1) * rng.choice([-1, 1])
        centers[i, :] = corpus[c]
        targets[i, 0] = corpus[c + off]
        labels[i, 0] = 1.0
        targets[i, 1:] = rng.randint(0, vocab, k_neg)
    return centers, targets, labels


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--topics", type=int, default=4)
    ap.add_argument("--words-per-topic", type=int, default=16)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--window", type=int, default=2)
    ap.add_argument("--k-neg", type=int, default=5)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    vocab = args.topics * args.words_per_topic
    rng = np.random.RandomState(args.seed)
    corpus = make_corpus(rng, args.topics, args.words_per_topic, 20000)
    C, T, L = make_pairs(rng, corpus, args.window, vocab, args.k_neg, 8192)

    class NCEModel(gluon.nn.HybridBlock):
        """in/out embedding tables + per-word output bias; the forward
        scores a (B, 1+k) slate of candidate targets per center."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed_in = gluon.nn.Embedding(vocab, args.dim)
                self.embed_out = gluon.nn.Embedding(vocab, args.dim)
                self.bias = gluon.nn.Embedding(vocab, 1)

        def hybrid_forward(self, F, center, target):
            vi = self.embed_in(center)              # (B, 1+k, D)
            vo = self.embed_out(target)             # (B, 1+k, D)
            b = self.bias(target).reshape((0, -1))  # (B, 1+k)
            return F.sum(vi * vo, axis=-1) + b      # logits

    net = NCEModel()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(C)
    first_loss = last_loss = None
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        tot, nb = 0.0, 0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            c, t = nd.array(C[idx]), nd.array(T[idx])
            y = nd.array(L[idx])
            with autograd.record():
                loss = bce(net(c, t), y).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
            nb += 1
        avg = tot / nb
        if first_loss is None:
            first_loss = avg
        last_loss = avg
        print(f"epoch {epoch} nce loss {avg:.4f}")

    # embeddings must cluster by topic: mean intra-topic cosine similarity
    # should dominate inter-topic
    W = net.embed_in.weight.data().asnumpy()
    W = W / (np.linalg.norm(W, axis=1, keepdims=True) + 1e-8)
    sim = W @ W.T
    wpt = args.words_per_topic
    intra, inter, cnt_a, cnt_e = 0.0, 0.0, 0, 0
    for i in range(vocab):
        for j in range(i + 1, vocab):
            if i // wpt == j // wpt:
                intra += sim[i, j]; cnt_a += 1
            else:
                inter += sim[i, j]; cnt_e += 1
    intra, inter = intra / cnt_a, inter / cnt_e
    print(f"loss first {first_loss:.4f} last {last_loss:.4f}; "
          f"cosine intra-topic {intra:.3f} vs inter-topic {inter:.3f}")
    assert last_loss < first_loss * 0.8, (first_loss, last_loss)
    assert intra > inter + 0.1, (intra, inter)
    print("NCE_OK")


if __name__ == "__main__":
    main()
