#!/usr/bin/env python
"""CNN text classification (reference
example/cnn_text_classification/text_cnn.py — Kim 2014).

Multi-width 1-D convolutions over an embedded token sequence, max-over-
time pooling, concat, dense classifier. The synthetic task plants class-
specific trigram patterns into random token streams, so the conv filters
must learn n-gram detectors — exactly what the architecture is for.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_data(rng, n, seq_len, vocab, n_classes):
    """Random token streams with one class-specific trigram planted per
    sample; pattern tokens [0, n_classes) are reserved out of the random
    vocabulary so the trigram is the only class signal."""
    if vocab <= n_classes + 2:
        raise ValueError(f"vocab ({vocab}) must exceed n_classes+2 "
                         f"({n_classes + 2}) to leave random tokens")
    X = rng.randint(n_classes, vocab, (n, seq_len))
    y = rng.randint(0, n_classes, n)
    for i in range(n):
        c = int(y[i])
        pat = [c, (c + 1) % n_classes, (c + 2) % n_classes]
        pos = rng.randint(0, seq_len - 3)
        X[i, pos:pos + 3] = pat
    return X.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=24)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--embed", type=int, default=16)
    ap.add_argument("--filters", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    Xtr, ytr = make_data(rng, 768, args.seq_len, args.vocab, args.classes)
    Xte, yte = make_data(rng, 256, args.seq_len, args.vocab, args.classes)

    class TextCNN(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.Embedding(args.vocab, args.embed)
                self.convs = []
                for i, width in enumerate((2, 3, 4)):
                    conv = gluon.nn.Conv1D(args.filters, width,
                                           activation="relu")
                    setattr(self, f"conv{i}", conv)
                    self.convs.append(conv)
                self.pool = gluon.nn.GlobalMaxPool1D()
                self.drop = gluon.nn.Dropout(0.2)
                self.out = gluon.nn.Dense(args.classes)

        def hybrid_forward(self, F, x):
            e = self.embed(x)                     # (B, T, E)
            e = F.transpose(e, axes=(0, 2, 1))    # (B, E, T) for Conv1D
            feats = [F.flatten(self.pool(c(e))) for c in self.convs]
            h = F.concat(*feats, dim=1)
            return self.out(self.drop(h))

    net = TextCNN()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for ep in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot, nb = 0.0, 0
        for i in range(0, len(Xtr), args.batch_size):
            idx = perm[i:i + args.batch_size]
            xb, yb = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(len(idx))
            tot += float(loss.mean().asnumpy())
            nb += 1
        if ep % 2 == 0:
            print(f"epoch {ep}: loss {tot / nb:.4f}")

    pred = net(nd.array(Xte)).asnumpy().argmax(1)
    acc = (pred == yte).mean()
    print(f"test accuracy: {acc:.3f}")
    assert acc > 0.6, acc
    print("TEXTCNN_OK", acc)


if __name__ == "__main__":
    main()
