#!/usr/bin/env python
"""Adversarial examples via FGSM (reference example/adversary/adversary_generation.ipynb
— fast gradient sign method on an MNIST classifier).

Train a small convnet on synthetic glyph digits, then take the gradient
of the loss WITH RESPECT TO THE INPUT IMAGE (autograd through a frozen
net into pixels), perturb by eps*sign(grad), and measure the accuracy
collapse; finally adversarially fine-tune on the perturbed batch and
show robustness recovering — the full classic demonstration.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_CLASSES = 10
IMG = 16


def make_data(rng, glyphs, n):
    y = rng.randint(0, N_CLASSES, n)
    X = glyphs[y] + 0.35 * rng.randn(n, 1, IMG, IMG).astype(np.float32)
    return np.clip(X, 0, 1).astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--adv-epochs", type=int, default=7)
    ap.add_argument("--eps", type=float, default=0.32)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(args.seed)
    glyphs = (rng.rand(N_CLASSES, 1, IMG, IMG) > 0.55).astype(np.float32)
    Xtr, ytr = make_data(rng, glyphs, 1024)
    Xte, yte = make_data(rng, glyphs, 256)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(N_CLASSES))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def train_on(X, y, epochs):
        n = len(X)
        for _ in range(epochs):
            perm = rng.permutation(n)
            for s in range(0, n - args.batch_size + 1, args.batch_size):
                idx = perm[s:s + args.batch_size]
                with autograd.record():
                    loss = sce(net(nd.array(X[idx])),
                               nd.array(y[idx])).mean()
                loss.backward()
                trainer.step(1)

    def accuracy(X, y):
        return float((net(nd.array(X)).asnumpy().argmax(1) == y).mean())

    def fgsm(X, y, eps):
        """Perturb inputs along sign(dL/dx) — gradient wrt the IMAGE."""
        x = nd.array(X)
        x.attach_grad()
        with autograd.record():
            loss = sce(net(x), nd.array(y)).mean()
        loss.backward()
        adv = x + eps * nd.sign(x.grad)
        return np.clip(adv.asnumpy(), 0, 1)

    train_on(Xtr, ytr, args.epochs)
    clean = accuracy(Xte, yte)
    Xadv = fgsm(Xte, yte, args.eps)
    attacked = accuracy(Xadv, yte)
    print(f"clean accuracy {clean:.3f} -> under FGSM(eps={args.eps}) "
          f"{attacked:.3f}")
    assert clean > 0.85, clean
    assert attacked < clean - 0.3, (clean, attacked)  # the attack must bite

    # adversarial training: fine-tune on freshly-generated adversarial
    # batches of the TRAIN set, then re-attack the test set
    for _ in range(args.adv_epochs):
        Xadv_tr = fgsm(Xtr, ytr, args.eps)
        train_on(np.concatenate([Xtr, Xadv_tr]),
                 np.concatenate([ytr, ytr]), 1)
    robust = accuracy(fgsm(Xte, yte, args.eps), yte)
    print(f"after adversarial training: FGSM accuracy {robust:.3f}")
    assert robust > attacked + 0.2, (attacked, robust)
    print("FGSM_OK")


if __name__ == "__main__":
    main()
