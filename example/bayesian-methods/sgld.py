#!/usr/bin/env python
"""Stochastic Gradient Langevin Dynamics (reference
example/bayesian-methods/sgld.ipynb — Welling & Teh: SGD whose updates
inject Gaussian noise scaled to the step size, so the iterates SAMPLE
the posterior instead of collapsing to the MAP point).

Bayesian logistic regression on a separable synthetic problem. Two
things distinguish a posterior sampler from an optimizer, and both are
asserted: (1) predictive accuracy from averaging posterior samples is
high, and (2) the between-sample variance of the weights stays bounded
AWAY from zero (an optimizer's iterates collapse; SGLD's equilibrium
fluctuation matches the posterior spread), with uncertainty growing on
points far from the data.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

DIM = 8


def make_data(rng, n, w_true):
    X = rng.randn(n, DIM).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    return X, (rng.rand(n) < p).astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=3000)
    ap.add_argument("--burnin", type=int, default=1000)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--prior-prec", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, nd

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    w_true = rng.randn(DIM).astype(np.float32) * 2.0
    Xtr, ytr = make_data(rng, 512, w_true)
    Xte, yte = make_data(rng, 256, w_true)
    n = len(Xtr)

    w = nd.zeros((DIM,))
    samples = []
    for t in range(args.steps):
        idx = rng.randint(0, n, args.batch_size)
        xb, yb = nd.array(Xtr[idx]), nd.array(ytr[idx])
        w.attach_grad()
        with autograd.record():
            logits = nd.dot(xb, w)
            # minibatch log-lik scaled to the full dataset + Gaussian prior
            loglik = -nd.mean(nd.log(1 + nd.exp(-logits)) * yb +
                              nd.log(1 + nd.exp(logits)) * (1 - yb)) * n
            logprior = -0.5 * args.prior_prec * nd.sum(w ** 2)
            logpost = loglik + logprior
        logpost.backward()
        eps = args.lr / (1.0 + t / 500.0)         # decaying step size
        noise = nd.array(rng.randn(DIM).astype(np.float32))
        # THE SGLD update: gradient ascent + sqrt(eps) Langevin noise
        w = w + 0.5 * eps * w.grad + noise * float(np.sqrt(eps))
        if t >= args.burnin and t % 10 == 0:
            samples.append(w.asnumpy().copy())

    S = np.stack(samples)                          # (K, DIM) posterior draws
    print(f"{len(S)} posterior samples, weight spread "
          f"{S.std(axis=0).mean():.4f}")

    def sigmoid(z):                # overflow-stable
        return np.where(z >= 0, 1.0 / (1.0 + np.exp(-np.abs(z))),
                        np.exp(-np.abs(z)) / (1.0 + np.exp(-np.abs(z))))

    # (1) Bayesian model averaging predicts well
    probs = sigmoid(Xte @ S.T)                     # (n, K)
    acc = float(((probs.mean(1) > 0.5) == yte).mean())
    print(f"posterior-averaged accuracy: {acc:.3f}")
    assert acc > 0.85, acc

    # (2) genuine posterior spread: samples fluctuate (not MAP-collapsed)
    # but stay concentrated around truth's direction
    spread = S.std(axis=0).mean()
    assert 0.01 < spread < 1.0, spread
    cos = float(S.mean(0) @ w_true /
                (np.linalg.norm(S.mean(0)) * np.linalg.norm(w_true)))
    print(f"cosine(posterior mean, true w) = {cos:.3f}")
    assert cos > 0.9, cos

    # (3) predictive uncertainty is higher far from the data manifold
    far = 20.0 * rng.randn(256, DIM).astype(np.float32)
    pf = sigmoid(far @ S.T)
    # disagreement ACROSS posterior samples is the Bayesian uncertainty
    # signal; it must grow off the data manifold
    var_near = probs.std(axis=1).mean()
    var_far = pf.std(axis=1).mean()
    print(f"between-sample predictive std: near {var_near:.4f} "
          f"far {var_far:.4f}")
    assert var_far > var_near, (var_near, var_far)
    print("SGLD_OK")


if __name__ == "__main__":
    main()
