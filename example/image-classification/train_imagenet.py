#!/usr/bin/env python
"""ImageNet-class training entry point (the reference's north-star command:
`train_imagenet.py --kv-store tpu`).

Reference: example/image-classification/train_imagenet.py + common/fit.py.
TPU-native: with --kv-store tpu the whole step (fwd+bwd+allreduce+update)
is ONE pjit'd XLA program over a dp mesh (parallel.TrainStep); `local`
runs the eager Gluon Trainer path. Data comes from an ImageRecordIter
.rec file when --data-train is given, else a synthetic stream (for
benchmarking and smoke tests, like benchmark_score.py's dummy data).
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="train imagenet",
                                formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--network", default="resnet50_v1",
                   help="gluon.model_zoo.vision model name")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--num-batches", type=int, default=50,
                   help="batches per epoch for synthetic data")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--mom", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=1e-4)
    p.add_argument("--kv-store", default="tpu",
                   choices=["local", "device", "tpu", "dist_sync"])
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--data-train", default=None, help=".rec file (optional)")
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel mesh size (0 = all devices)")
    p.add_argument("--disp-batches", type=int, default=10)
    return p.parse_args()


def get_data(args, shape):
    import incubator_mxnet_tpu as mx
    if args.data_train:
        return mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=shape,
            batch_size=args.batch_size, shuffle=True)
    rs = np.random.RandomState(0)
    X = rs.normal(0, 1, (args.batch_size,) + shape).astype(np.float32)
    Y = rs.randint(0, args.num_classes, args.batch_size).astype(np.float32)

    class Synthetic:
        def __iter__(self):
            for _ in range(args.num_batches):
                yield mx.nd.array(X), mx.nd.array(Y)

        def reset(self):
            pass

    return Synthetic()


def main():
    args = parse_args()
    logging.basicConfig(level=logging.INFO)
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    shape = tuple(int(x) for x in args.image_shape.split(","))
    net = getattr(vision, args.network)(classes=args.num_classes)
    net.initialize(mx.init.Xavier(magnitude=2.0))
    data = get_data(args, shape)

    if args.kv_store in ("tpu", "device"):
        # compiled SPMD path: dp mesh over all chips, ONE XLA program/step
        from incubator_mxnet_tpu.parallel import TrainStep, make_mesh

        ndev = args.dp or len(jax.devices())
        mesh = make_mesh({"dp": ndev}) if ndev > 1 else None

        def loss_fn(out, label):
            logp = jax.nn.log_softmax(out.astype(jnp.float32), -1)
            return -jnp.mean(jnp.take_along_axis(
                logp, label.astype(jnp.int32)[:, None], 1))

        x0 = mx.nd.array(np.zeros((args.batch_size,) + shape, np.float32))
        step = TrainStep(net, loss_fn, optimizer="sgd",
                         optimizer_params={"learning_rate": args.lr,
                                           "momentum": args.mom,
                                           "wd": args.wd},
                         mesh=mesh, example_inputs=[x0],
                         dtype=None if args.dtype == "float32" else args.dtype)
        for epoch in range(args.num_epochs):
            tic = time.time()
            n = 0
            for i, (x, y) in enumerate(data):
                loss = step(x, y)
                n += args.batch_size
                if (i + 1) % args.disp_batches == 0:
                    logging.info("epoch %d batch %d loss %.4f  %.1f img/s",
                                 epoch, i + 1, float(loss.asnumpy() if
                                 hasattr(loss, "asnumpy") else loss),
                                 n / (time.time() - tic))
            data.reset()
            step.sync()
            logging.info("epoch %d done: %.1f img/s", epoch,
                         n / (time.time() - tic))
    else:
        from incubator_mxnet_tpu import autograd, gluon
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": args.lr,
                                 "momentum": args.mom, "wd": args.wd},
                                kvstore=args.kv_store)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        for epoch in range(args.num_epochs):
            tic = time.time()
            n = 0
            for i, (x, y) in enumerate(data):
                with autograd.record():
                    loss = loss_fn(net(x), y).mean()
                loss.backward()
                trainer.step(args.batch_size)
                n += args.batch_size
                if (i + 1) % args.disp_batches == 0:
                    logging.info("epoch %d batch %d loss %.4f  %.1f img/s",
                                 epoch, i + 1, float(loss.asnumpy()),
                                 n / (time.time() - tic))
            data.reset()
            logging.info("epoch %d done: %.1f img/s", epoch,
                         n / (time.time() - tic))


if __name__ == "__main__":
    main()
