#!/usr/bin/env python
"""MNIST training via the Module API (reference
example/image-classification/train_mnist.py — BASELINE config 1).

Uses MNISTIter over idx/ubyte files when --data-dir has them, else a
synthetic digit stream so the script runs anywhere.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def mlp_symbol(sym, num_classes):
    data = sym.var("data")
    net = sym.flatten(data)
    net = sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = sym.Activation(net, act_type="relu", name="relu2")
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc3")
    return sym.SoftmaxOutput(net, name="softmax")


def lenet_symbol(sym, num_classes):
    data = sym.var("data")
    net = sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    net = sym.Activation(net, act_type="tanh", name="tanh1")
    net = sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = sym.Convolution(net, kernel=(5, 5), num_filter=50, name="conv2")
    net = sym.Activation(net, act_type="tanh", name="tanh2")
    net = sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = sym.flatten(net)
    net = sym.FullyConnected(net, num_hidden=500, name="fc1")
    net = sym.Activation(net, act_type="tanh", name="tanh3")
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def get_iters(args):
    import incubator_mxnet_tpu as mx
    tr_img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    tr_lab = os.path.join(args.data_dir, "train-labels-idx1-ubyte")
    if os.path.exists(tr_img):
        train = mx.io.MNISTIter(image=tr_img, label=tr_lab,
                                batch_size=args.batch_size, shuffle=True,
                                flat=args.network == "mlp")
        val_img = os.path.join(args.data_dir, "t10k-images-idx3-ubyte")
        val = mx.io.MNISTIter(image=val_img,
                              label=os.path.join(
                                  args.data_dir, "t10k-labels-idx1-ubyte"),
                              batch_size=args.batch_size,
                              flat=args.network == "mlp")
        return train, val
    # synthetic fallback: each class is a noisy template so the model can
    # actually learn
    rs = np.random.RandomState(7)
    templates = (rs.rand(10, 28, 28) > 0.5).astype(np.float32)
    n = args.num_examples
    ys = rs.randint(0, 10, n)
    xs = templates[ys] + rs.normal(0, 0.3, (n, 28, 28)).astype(np.float32)
    if args.network == "mlp":
        xs = xs.reshape(n, 784)
    else:
        xs = xs[:, None]
    split = int(0.9 * n)
    train = mx.io.NDArrayIter({"data": xs[:split]},
                              {"softmax_label": ys[:split].astype(np.float32)},
                              batch_size=args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter({"data": xs[split:]},
                            {"softmax_label": ys[split:].astype(np.float32)},
                            batch_size=args.batch_size)
    return train, val


def main():
    p = argparse.ArgumentParser(description="train mnist")
    p.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    p.add_argument("--data-dir", default="./mnist")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--num-examples", type=int, default=2000)
    p.add_argument("--lr", type=float, default=0.5)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import symbol as sym
    from incubator_mxnet_tpu.module import Module

    net = (mlp_symbol if args.network == "mlp" else lenet_symbol)(sym, 10)
    train, val = get_iters(args)
    mod = Module(net)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="sgd", optimizer_params={"learning_rate": args.lr},
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    score = mod.score(val, "acc")
    logging.info("final validation accuracy: %.4f", dict(score)["accuracy"])


if __name__ == "__main__":
    main()
