#!/usr/bin/env python
"""Inference throughput over the model zoo (reference
example/image-classification/benchmark_score.py — the source of
BASELINE.md's inference rows).

Scans batch sizes per network; each measurement runs its loop on-device
(lax.scan with carry feedback) so a tunneled device's dispatch RTT
doesn't pollute the number — same discipline as bench.py.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def score(network, batch, steps, dtype):
    import jax
    import jax.numpy as jnp
    from jax import lax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel.functional import functionalize

    net = getattr(vision, network)(classes=1000)
    net.initialize(mx.init.Xavier())
    if dtype != "float32":
        net.cast(dtype)
    size = 299 if "inception" in network else 224
    x0 = mx.nd.array(np.random.randn(batch, 3, size, size)
                     .astype(np.float32)).astype(dtype)
    params, apply_fn = functionalize(net, [x0], training=False)
    rng = jax.random.PRNGKey(0)
    xa = x0._data

    def loop(p, r, xx):
        def body(c, _):
            out = apply_fn(p, r, xx + c.astype(xx.dtype))[0][0]
            return out.astype(jnp.float32).mean() * 1e-12, None
        s, _ = lax.scan(body, jnp.float32(0), None, length=steps)
        return s

    fwd = jax.jit(loop)
    s = fwd(params, rng, xa)
    s.block_until_ready()
    np.asarray(s)
    t0 = time.perf_counter()
    s = fwd(params, rng, xa)
    s.block_until_ready()
    np.asarray(s)
    return batch * steps / (time.perf_counter() - t0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--networks",
                   default="alexnet,vgg16,resnet50_v1,resnet152_v1,"
                           "inception_v3,mobilenet1_0,densenet121,"
                           "squeezenet1_0")
    p.add_argument("--batch-sizes", default="1,32,128")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    args = p.parse_args()
    for net in args.networks.split(","):
        for b in (int(x) for x in args.batch_sizes.split(",")):
            try:
                ips = score(net, b, args.steps, args.dtype)
            except Exception as e:
                print(f"network: {net}, batch {b}: FAILED {e!r}")
                continue
            print(f"network: {net}, batch size: {b}, dtype: {args.dtype}, "
                  f"images/sec: {ips:.2f}")


if __name__ == "__main__":
    main()
