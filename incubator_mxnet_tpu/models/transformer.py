"""SPMD Transformer language model (GPT-style, pre-norm).

Purpose: the multi-parallel flagship — data (dp), tensor (tp, Megatron
column/row pairing), and sequence/context (sp, ring attention) parallelism in
ONE jitted train step over a jax.sharding.Mesh. The reference's closest
artifacts are the fused attention matmul ops (src/operator/contrib/
transformer.cc) and the PTB word_lm example; it has no TP/SP at all
(SURVEY.md §2.3), so this model is where the TPU build goes beyond parity.

Functional style: params = flat dict name -> jax.Array; every name maps to a
PartitionSpec via parallel.tensor_parallel.transformer_param_specs.
"""
from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from jax.ad_checkpoint import checkpoint_name

from ..parallel.ring_attention import attention_reference, ring_attention


def _remat_policy(name):
    """Map TransformerConfig.remat_policy to a jax.checkpoint policy
    (None = recompute everything; reference analog: the
    MXNET_BACKWARD_DO_MIRROR recompute knob, graph_executor.cc:351)."""
    if not name:
        return None
    cp = jax.checkpoint_policies
    table = {
        "dots": cp.checkpoint_dots,
        "dots_no_batch": cp.checkpoint_dots_with_no_batch_dims,
        "save_attn": cp.save_only_these_names("attn_out"),
        "save_attn_mlp": cp.save_only_these_names("attn_out", "mlp_out"),
        "save_mlp": cp.save_only_these_names("mlp_out"),
    }
    if name not in table:
        raise ValueError(f"unknown remat_policy {name!r}; "
                         f"one of {sorted(table)}")
    return table[name]

__all__ = ["TransformerConfig", "TransformerLM"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 2048
    dtype: str = "bfloat16"
    remat: bool = True          # jax.checkpoint each block (HBM for FLOPs)
    # Selective rematerialization policy (r4 profile: recompute is 199ms
    # = 18% of the flagship step, the largest untried lever). None =
    # recompute everything (baseline). "dots" / "dots_no_batch" are
    # XLA's stock save-matmul-outputs policies; "save_attn" /
    # "save_attn_mlp" save the named per-block outputs (attn_out, mlp_out
    # — 1.6 GB each per 12x1024/T2048/b32 model at bf16) and recompute
    # the rest. Measured results belong in docs/perf_notes.md.
    remat_policy: str | None = None
    # Pallas blocked flash attention for the non-sp path (O(T) memory,
    # parallel/flash_attention.py); the sp path always uses ring
    # attention. DEFAULT ON since round 4: steady-state train at T=2048
    # b32 measures 56.3k tok/s vs 39.9k with the dense path (the round-3
    # "flash loses end-to-end" number was a first-dispatch warmup
    # artifact — docs/perf_notes.md). Untileable shapes fall back to
    # attention_reference inside flash_attention().
    flash_attention: bool = True


class TransformerLM:
    def __init__(self, config: TransformerConfig):
        self.cfg = config

    # -- parameters ---------------------------------------------------------
    def init_params(self, key):
        cfg = self.cfg
        dt = jnp.dtype(cfg.dtype)
        d, h, f = cfg.d_model, cfg.n_heads, cfg.d_ff
        params = {}
        k = iter(jax.random.split(key, 4 + 8 * cfg.n_layers))

        def dense(key, fan_in, shape):
            return (jax.random.normal(key, shape, jnp.float32) /
                    math.sqrt(fan_in)).astype(dt)

        params["embed"] = dense(next(k), d, (cfg.vocab_size, d))
        params["pos_embed"] = dense(next(k), d, (cfg.max_len, d))
        for i in range(cfg.n_layers):
            p = f"layer{i}_"
            params[p + "ln1_g"] = jnp.ones((d,), dt)
            params[p + "ln1_b"] = jnp.zeros((d,), dt)
            params[p + "wq"] = dense(next(k), d, (d, d))
            params[p + "wk"] = dense(next(k), d, (d, d))
            params[p + "wv"] = dense(next(k), d, (d, d))
            params[p + "wo"] = dense(next(k), d, (d, d))
            params[p + "ln2_g"] = jnp.ones((d,), dt)
            params[p + "ln2_b"] = jnp.zeros((d,), dt)
            params[p + "w_in"] = dense(next(k), d, (d, f))
            params[p + "w_out"] = dense(next(k), f, (f, d))
        params["lnf_g"] = jnp.ones((d,), dt)
        params["lnf_b"] = jnp.zeros((d,), dt)
        return params

    # -- forward ------------------------------------------------------------
    def _ln(self, x, g, b):
        m = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
        v = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
        return ((x - m) * jax.lax.rsqrt(v + 1e-5)).astype(x.dtype) * g + b

    def _block(self, params, prefix, x, sp_axis, tp_axis=None):
        """One pre-norm block. Inside shard_map, attention/MLP weights may be
        Megatron-sharded over `tp_axis` (wq/wk/wv/w_in column-parallel,
        wo/w_out row-parallel): each device computes its local slice of heads
        / hidden units and a psum over tp after each row-parallel matmul
        restores the full residual stream. Head/hidden split is read off the
        *local* weight shapes, so the same code serves the unsharded path."""
        cfg = self.cfg
        B, T, D = x.shape
        hd = D // cfg.n_heads
        h = self._ln(x, params[prefix + "ln1_g"], params[prefix + "ln1_b"])
        wq = params[prefix + "wq"]
        d_local = wq.shape[1]          # = D/tp inside shard_map with TP
        h_local = d_local // hd        # local head count
        q = (h @ wq).reshape(B, T, h_local, hd)
        kk = (h @ params[prefix + "wk"]).reshape(B, T, h_local, hd)
        v = (h @ params[prefix + "wv"]).reshape(B, T, h_local, hd)
        if sp_axis is not None:
            attn = ring_attention(q, kk, v, sp_axis, causal=True)
        elif self.cfg.flash_attention:
            # measured r4: emitting (BH,T,hd) straight from projection
            # einsums to skip the _to_bh copies is 4.4% SLOWER end to end
            # (56.5k vs 59.1k tok/s) — XLA's bhtk-output einsum costs
            # more than the transposes it saves. Keep the standard
            # layout; flash_attention_bh stays for callers that already
            # hold (BH,T,D).
            from ..parallel.flash_attention import flash_attention
            attn = flash_attention(q, kk, v, causal=True)
        else:
            attn = attention_reference(q, kk, v, causal=True)
        attn_out = attn.reshape(B, T, d_local) @ params[prefix + "wo"]
        if tp_axis is not None:
            attn_out = jax.lax.psum(attn_out, tp_axis)
        attn_out = checkpoint_name(attn_out, "attn_out")
        x = x + attn_out
        h = self._ln(x, params[prefix + "ln2_g"], params[prefix + "ln2_b"])
        y = jax.nn.gelu(h @ params[prefix + "w_in"]) @ params[prefix + "w_out"]
        if tp_axis is not None:
            y = jax.lax.psum(y, tp_axis)
        y = checkpoint_name(y, "mlp_out")
        return x + y

    def apply(self, params, tokens, sp_axis=None, positions=None, tp_axis=None):
        """tokens (B, T) int32 -> logits (B, T, vocab). When called inside a
        shard_map with a sequence axis, pass sp_axis and per-shard positions;
        pass tp_axis when attention/MLP weights are Megatron-sharded."""
        cfg = self.cfg
        x = params["embed"][tokens]
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        x = x + params["pos_embed"][positions]
        if cfg.remat:
            block = jax.checkpoint(
                lambda p, pref, y: self._block(p, pref, y, sp_axis, tp_axis),
                static_argnums=(1,), policy=_remat_policy(cfg.remat_policy))
        else:
            block = lambda p, pref, y: self._block(p, pref, y, sp_axis, tp_axis)
        for i in range(cfg.n_layers):
            x = block(params, f"layer{i}_", x)
        x = self._ln(x, params["lnf_g"], params["lnf_b"])
        return (x @ params["embed"].T).astype(jnp.float32)

    def loss(self, params, tokens, targets, sp_axis=None, positions=None,
             tp_axis=None):
        logits = self.apply(params, tokens, sp_axis, positions, tp_axis)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    # -- sharded training ---------------------------------------------------
    def param_sharding(self, mesh, tp_axis="tp"):
        from ..parallel.tensor_parallel import transformer_param_specs
        has_tp = tp_axis in mesh.axis_names
        shd = {}
        for name in self._param_names():
            shd[name] = NamedSharding(
                mesh, transformer_param_specs(name, _FakeNd(2), tp_axis)
                if has_tp and _rank_of(name) >= 2 else P())
        return shd

    def _param_names(self):
        names = ["embed", "pos_embed", "lnf_g", "lnf_b"]
        for i in range(self.cfg.n_layers):
            p = f"layer{i}_"
            names += [p + s for s in ("ln1_g", "ln1_b", "wq", "wk", "wv", "wo",
                                      "ln2_g", "ln2_b", "w_in", "w_out")]
        return names

    def make_train_step(self, mesh, lr=1e-3, use_sp=True, n_steps=None):
        """Fully-sharded train step: dp on batch, tp on weights, sp on
        sequence (ring attention through shard_map). Adam in fp32 master
        precision. Returns (step_fn, shard_params_fn, init_opt_fn);
        step_fn(params, opt_state, tokens, targets, step_i) -> (params,
        opt_state, loss) with params/opt_state donated.

        n_steps: compile a MULTI-step program — lax.scan of the step with
        params/opt carried on device, one dispatch for the whole window
        (the TrainStep.run_steps analog; per-step RNG/step_i advance in
        the scan)."""
        from ..parallel._compat import shard_map
        from ..parallel.tensor_parallel import transformer_param_specs

        axis_names = mesh.axis_names
        has = {a: a in axis_names for a in ("dp", "tp", "sp")}
        sp_axis = "sp" if (use_sp and has["sp"]) else None

        def _is_matmul(n):
            return n.endswith(("wq", "wk", "wv", "wo", "w_in", "w_out"))

        # weights are tp-sharded only when the mesh actually has a 'tp' axis.
        # On the shard_map (sp) path the block does manual Megatron TP, so
        # only the attention/MLP matmul weights are sharded and the embedding
        # stays replicated (apply() indexes the full table in-shard); on the
        # pure-jit GSPMD path XLA handles any spec, embedding included.
        if sp_axis is not None:
            pspec = {n: (transformer_param_specs(n, _FakeNd(2))
                         if has["tp"] and _is_matmul(n) else P())
                     for n in self._param_names()}
        else:
            pspec = {n: (transformer_param_specs(n, _FakeNd(2))
                         if has["tp"] and _rank_of(n) >= 2 else P())
                     for n in self._param_names()}
        data_spec = P("dp" if has["dp"] else None,
                      sp_axis)

        model = self
        tp_in_block = "tp" if (sp_axis is not None and has["tp"]) else None

        def loss_fn(params, tokens, targets):
            if sp_axis is not None:
                # sequence-sharded path: positions differ per shard
                def local(params_, tokens_, targets_):
                    idx = jax.lax.axis_index(sp_axis)
                    t_local = tokens_.shape[1]
                    positions = idx * t_local + jnp.arange(t_local)
                    l = model.loss(params_, tokens_, targets_, sp_axis,
                                   positions, tp_in_block)
                    terms = jax.lax.pmean(l, sp_axis)
                    if has["dp"]:
                        terms = jax.lax.pmean(terms, "dp")
                    if has["tp"]:
                        terms = jax.lax.pmean(terms, "tp")
                    return terms

                fn = shard_map(local, mesh,
                               (pspec, data_spec, data_spec), P())
                return fn(params, tokens, targets)
            return model.loss(params, tokens, targets)

        from ..parallel.train import _make_update_rule
        _, adam_rule = _make_update_rule("adam", lr, 0.0, 0.0, {})

        def step(params, opt_state, tokens, targets, step_i):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
            new_params, new_opt = {}, {}
            t = step_i + 1
            for k, g in grads.items():
                # fp32 master weights around the shared adam rule
                w32, new_opt[k] = adam_rule(params[k].astype(jnp.float32),
                                            g.astype(jnp.float32),
                                            opt_state[k], t)
                new_params[k] = w32.astype(params[k].dtype)
            return new_params, new_opt, loss

        if n_steps:
            from jax import lax

            def multi(params, opt_state, tokens, targets, step0,
                      _one=step):
                def body(carry, i):
                    p, o = carry
                    p, o, l = _one(p, o, tokens, targets, step0 + i)
                    return (p, o), l
                (p, o), losses = lax.scan(body, (params, opt_state),
                                          jnp.arange(n_steps))
                return p, o, losses[-1]

            step = multi

        in_shardings = (
            {n: NamedSharding(mesh, s) for n, s in pspec.items()},
            {n: (NamedSharding(mesh, pspec[n]), NamedSharding(mesh, pspec[n]))
             for n in pspec},
            NamedSharding(mesh, data_spec),
            NamedSharding(mesh, data_spec),
            None,
        )
        jit_step = jax.jit(step, in_shardings=in_shardings,
                           donate_argnums=(0, 1))

        def shard_params(params):
            # jnp.asarray copy first: device_put may alias the source buffer
            # (zero-copy on CPU), and the donated step would then delete the
            # caller's arrays with it
            return {k: jax.device_put(jnp.asarray(v).copy(),
                                      NamedSharding(mesh, pspec[k]))
                    for k, v in params.items()}

        def init_opt(params):
            return {k: (jnp.zeros(v.shape, jnp.float32),
                        jnp.zeros(v.shape, jnp.float32))
                    for k, v in params.items()}

        return jit_step, shard_params, init_opt


def _rank_of(name):
    if name in ("embed", "pos_embed") or name.endswith(("wq", "wk", "wv", "wo",
                                                        "w_in", "w_out")):
        return 2
    return 1


class _FakeNd:
    def __init__(self, ndim):
        self.ndim = ndim
        self.shape = (1,) * ndim
