"""Composed-parallel MoE transformer: dp x pp x tp x sp x ep in ONE step.

This is the all-axes flagship the reference cannot express at all — its
only model parallelism is static layer placement with no pipelining
(`group2ctx`, reference src/executor/graph_executor.cc:986) and it has no
TP/SP/EP. Here a single jitted shard_map over one jax.sharding.Mesh
composes:

  dp  — batch sharding, gradients meaned across the axis (by shard_map's
        autodiff transpose of the loss pmean; no explicit allreduce),
  pp  — layers split into stages; microbatches flow through a ppermute
        ring (parallel/pipeline.py). The BACKWARD schedule is selectable
        (`schedule=`, env MXTPU_PP_SCHEDULE): "gpipe" differentiates the
        forward scan, so the backward is its transpose — all-forward then
        all-backward, every microbatch's activations live at once —
        while "1f1b" runs a one-forward-one-backward steady state where
        backward for microbatch k overlaps forward for microbatch k+S
        and at most 2(S−1−s)+1 stage inputs are in flight per stage,
        with per-stage recompute standing in for stored activations
        (remat=, env MXNET_REMAT). Cross-ROUND gradient accumulation is
        explicit: the local batch is chunked into rounds scanned
        sequentially, so activation memory is bounded by one round's
        pipeline.
  tp  — Megatron column/row sharding of attention + FFN matmuls with one
        psum after each row-parallel matmul,
  sp  — sequence sharding with ring attention (parallel/ring_attention.py),
  ep  — MoE expert sharding with GShard all-to-all token dispatch
        (parallel/moe.py moe_apply_a2a); experts ride a dedicated `ep`
        axis when the mesh has one, else the data-parallel axis (the
        GShard layout).

Every axis is optional: the step builder reads the mesh's axis names and
degrades to the axes present, so the same code serves {dp}, {dp,pp,tp},
{dp,pp,sp} ... meshes.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel._compat import shard_map
from ..parallel.moe import moe_apply, moe_apply_a2a
from ..parallel.pipeline import (REMAT_MODES, SCHEDULES, pipeline_train_apply,
                                 remat_stage_fn, schedule_stats)
from ..parallel.ring_attention import attention_reference, ring_attention

__all__ = ["ComposedConfig", "ComposedPipelineLM"]


@dataclasses.dataclass(frozen=True)
class ComposedConfig:
    vocab_size: int = 1024
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 4          # total; must divide by the mesh's pp size
    d_ff: int = 256
    n_experts: int = 4         # per MoE block; divisible by the ep size
    moe_every: int = 2         # within a stage, every k-th block is MoE
    capacity_factor: float = 2.0
    aux_weight: float = 0.01   # MoE load-balance loss weight
    max_len: int = 256
    dtype: str = "float32"


class ComposedPipelineLM:
    """Stage-stacked parameter layout: every per-block tensor has a
    leading stage dim S (sharded over pp); block j of every stage has the
    same FFN kind (dense or MoE) so the stacks stay uniform."""

    def __init__(self, cfg: ComposedConfig):
        self.cfg = cfg

    def _ffn_kind(self, j):
        if self.cfg.moe_every <= 0:
            return "dense"
        return "moe" if (j % self.cfg.moe_every == self.cfg.moe_every - 1) \
            else "dense"

    # -- parameters --------------------------------------------------------
    def init_params(self, key, n_stages, n_chunks=1):
        """Stage-stacked parameters: every per-block tensor leads with the
        stage dim S, or with (v, S) when `n_chunks` > 1 for the
        interleaved schedule — index [c, s] holds VIRTUAL stage c*S + s
        (the loop layout: sharding dim 1 over pp hands rank r exactly its
        v chunks, and the dense oracle walks virtual stages in vs
        order)."""
        cfg = self.cfg
        if cfg.n_layers % (n_stages * n_chunks):
            raise ValueError(f"n_layers {cfg.n_layers} not divisible by "
                             f"pp stages*chunks {n_stages}x{n_chunks}")
        lps = cfg.n_layers // (n_stages * n_chunks)
        dt = jnp.dtype(cfg.dtype)
        d, f, E = cfg.d_model, cfg.d_ff, cfg.n_experts
        keys = iter(jax.random.split(key, 4 + 16 * cfg.n_layers))
        lead = (n_chunks, n_stages) if n_chunks > 1 else (n_stages,)

        def dense(fan_in, shape):
            return (jax.random.normal(next(keys), shape, jnp.float32) /
                    math.sqrt(fan_in)).astype(dt)

        def stacked(fan_in, shape):
            return (jax.random.normal(next(keys), lead + shape,
                                      jnp.float32) / math.sqrt(fan_in)
                    ).astype(dt)

        params = {
            "embed": dense(d, (cfg.vocab_size, d)),
            "pos_embed": dense(d, (cfg.max_len, d)),
            "lnf_g": jnp.ones((d,), dt),
            "lnf_b": jnp.zeros((d,), dt),
        }
        for j in range(lps):
            b = f"b{j}_"
            params[b + "ln1_g"] = jnp.ones(lead + (d,), dt)
            params[b + "ln1_b"] = jnp.zeros(lead + (d,), dt)
            params[b + "wq"] = stacked(d, (d, d))
            params[b + "wk"] = stacked(d, (d, d))
            params[b + "wv"] = stacked(d, (d, d))
            params[b + "wo"] = stacked(d, (d, d))
            params[b + "ln2_g"] = jnp.ones(lead + (d,), dt)
            params[b + "ln2_b"] = jnp.zeros(lead + (d,), dt)
            if self._ffn_kind(j) == "moe":
                params[b + "wg"] = stacked(d, (d, E))
                params[b + "w1"] = stacked(d, (E, d, f))
                params[b + "w2"] = stacked(f, (E, f, d))
            else:
                params[b + "w_in"] = stacked(d, (d, f))
                params[b + "w_out"] = stacked(f, (f, d))
        return params

    # -- building blocks ---------------------------------------------------
    @staticmethod
    def _ln(x, g, b):
        m = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
        v = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
        return ((x - m) * lax.rsqrt(v + 1e-5)).astype(x.dtype) * g + b

    def _block(self, p, b, x, *, sp_axis, tp_axis, ep_axis, kind):
        """One pre-norm block on (mb, T_local, D). Weight tensors arrive
        already LOCAL (stage-sliced, tp/ep-sharded by shard_map)."""
        cfg = self.cfg
        B, T, D = x.shape
        hd = D // cfg.n_heads
        h = self._ln(x, p[b + "ln1_g"], p[b + "ln1_b"])
        d_local = p[b + "wq"].shape[1]
        q = (h @ p[b + "wq"]).reshape(B, T, d_local // hd, hd)
        k = (h @ p[b + "wk"]).reshape(B, T, d_local // hd, hd)
        v = (h @ p[b + "wv"]).reshape(B, T, d_local // hd, hd)
        if sp_axis is not None:
            attn = ring_attention(q, k, v, sp_axis, causal=True)
        else:
            attn = attention_reference(q, k, v, causal=True)
        attn_out = attn.reshape(B, T, d_local) @ p[b + "wo"]
        if tp_axis is not None:
            attn_out = lax.psum(attn_out, tp_axis)
        x = x + attn_out
        h = self._ln(x, p[b + "ln2_g"], p[b + "ln2_b"])
        aux = jnp.float32(0)
        if kind == "moe":
            flat = h.reshape(B * T, D)
            moe_p = {"wg": p[b + "wg"], "w1": p[b + "w1"], "w2": p[b + "w2"]}
            if ep_axis is not None:
                y, aux = moe_apply_a2a(flat, moe_p, ep_axis,
                                       capacity_factor=cfg.capacity_factor)
            else:
                y, aux = moe_apply(flat, moe_p,
                                   capacity_factor=cfg.capacity_factor)
            y = y.reshape(B, T, D)
        else:
            y = jax.nn.gelu(h @ p[b + "w_in"]) @ p[b + "w_out"]
            if tp_axis is not None:
                y = lax.psum(y, tp_axis)
        return x + y, aux

    # -- composed train step ----------------------------------------------
    def param_specs(self, mesh, n_chunks=1):
        """PartitionSpec per param name for a stage-stacked tree; with
        `n_chunks` > 1 the (v, S)-stacked tensors shard dim 1 over pp
        (the chunk dim stays local — every rank holds its v chunks)."""
        names = set(mesh.axis_names)
        pp = "pp" if "pp" in names else None
        tp = "tp" if "tp" in names else None
        ep = "ep" if "ep" in names else ("dp" if "dp" in names else None)
        specs = {}
        lps = self.cfg.n_layers // (
            (mesh.shape["pp"] if pp else 1) * n_chunks)
        lead = (None, pp) if n_chunks > 1 else (pp,)
        specs["embed"] = P()
        specs["pos_embed"] = P()
        specs["lnf_g"] = P()
        specs["lnf_b"] = P()
        for j in range(lps):
            b = f"b{j}_"
            for s in ("ln1_g", "ln1_b", "ln2_g", "ln2_b"):
                specs[b + s] = P(*lead)
            for s in ("wq", "wk", "wv"):       # column-parallel
                specs[b + s] = P(*lead, None, tp)
            specs[b + "wo"] = P(*lead, tp, None)  # row-parallel
            if self._ffn_kind(j) == "moe":
                specs[b + "wg"] = P(*lead)
                specs[b + "w1"] = P(*lead, ep)
                specs[b + "w2"] = P(*lead, ep)
            else:
                specs[b + "w_in"] = P(*lead, None, tp)
                specs[b + "w_out"] = P(*lead, tp, None)
        return specs

    def make_train_step(self, mesh, n_microbatches=2, grad_accum_rounds=1,
                        lr=1e-3, schedule=None, remat=None, n_chunks=None,
                        offload=None):
        """Returns (step_fn, shard_params, init_opt). step_fn(params, opt,
        tokens, targets, step_i) -> (params, opt, loss); tokens/targets
        (B, T) int32 sharded (dp, sp). ONE jitted program contains the
        full pipeline fwd+bwd schedule, every collective, and Adam.

        `schedule` picks the pipeline backward ("gpipe" / "1f1b" /
        "interleaved" / "zb1", default env MXTPU_PP_SCHEDULE) and `remat`
        the per-stage rematerialization policy ("none"/"dots_saveable"/
        "full", default env MXNET_REMAT); both also apply to the no-pp
        microbatch scan (where remat still bounds activation memory and
        schedule is moot). "interleaved" additionally takes `n_chunks`
        virtual-stage chunks per rank (default env MXTPU_PP_VSTAGES;
        params must come from init_params(..., n_chunks=v)), and
        `offload` (default env MXNET_PP_OFFLOAD) stages saved activations
        to host memory through the save_and_offload checkpoint policy —
        it overrides `remat`, which must stay "none"/"full" alongside it.
        The returned step carries `.schedule`, `.remat`, `.n_chunks`,
        `.offload`, `.bubble_fraction` (the schedule-grid idle fraction),
        `.schedule_stats`, `.jit_key` and `._cached` (the underlying
        cached_jit wrapper), and — when step attribution is on — books
        each call's wall time into the `compute` / `pp_bubble` phases so
        profiler.mfu_stats() reports the measured bubble, plus the
        per-step host-offload traffic on the `d2h_bytes` counter when
        offloading."""
        from ..util import getenv_bool, getenv_int, getenv_str
        if schedule is None:
            schedule = getenv_str("MXTPU_PP_SCHEDULE")
        if remat is None:
            remat = getenv_str("MXNET_REMAT")
        if offload is None:
            offload = getenv_bool("MXNET_PP_OFFLOAD")
        if schedule not in SCHEDULES:
            # the env knob lands here too: name every valid schedule
            # instead of surfacing a raw KeyError from a grid lookup
            raise ValueError(
                f"schedule {schedule!r} not in {SCHEDULES} "
                "(set MXTPU_PP_SCHEDULE or pass schedule=)")
        if remat not in REMAT_MODES:
            raise ValueError(f"remat {remat!r} not in {REMAT_MODES}")
        if offload and remat not in ("none", "full"):
            raise ValueError(
                f"offload overrides the remat policy; remat={remat!r} "
                "cannot compose with it — use remat='none' or 'full'")
        if n_chunks is None:
            n_chunks = getenv_int("MXTPU_PP_VSTAGES") \
                if schedule == "interleaved" else 1
        v = max(int(n_chunks), 1)
        if v > 1 and schedule != "interleaved":
            raise ValueError(
                f"n_chunks={v} only applies to schedule='interleaved', "
                f"not {schedule!r}")
        cfg = self.cfg
        if cfg.n_layers % ((mesh.shape["pp"] if "pp" in
                            set(mesh.axis_names) else 1) * v):
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by pp "
                f"stages*chunks")
        names = set(mesh.axis_names)
        dp = "dp" if "dp" in names else None
        pp = "pp" if "pp" in names else None
        tp = "tp" if "tp" in names else None
        sp = "sp" if "sp" in names else None
        ep = "ep" if "ep" in names else dp
        S = mesh.shape[pp] if pp else 1
        lps = cfg.n_layers // (S * v)
        model = self
        specs = self.param_specs(mesh, n_chunks=v)
        data_spec = P(dp, sp)
        mesh_axes = [a for a in (dp, pp, tp, sp,
                                 "ep" if "ep" in names else None) if a]

        def stage_fn(stage_p, h):
            aux_total = jnp.float32(0)
            for j in range(lps):
                h, aux = model._block(stage_p, f"b{j}_", h, sp_axis=sp,
                                      tp_axis=tp, ep_axis=ep,
                                      kind=model._ffn_kind(j))
                aux_total = aux_total + aux
            return h, aux_total

        def local_loss(params, tokens, targets):
            # stage-stacked tensors (the b*_ block params) arrive with a
            # local stage dim of 1 under a pp axis, or S=1 without one —
            # either way the local stage is slice 0; (v, S)-stacked
            # tensors keep their local chunk dim (v, 1, ...) -> (v, ...)
            stage_p = {k: ((p[:, 0] if v > 1 else p[0])
                           if k.startswith("b") else p)
                       for k, p in params.items()}
            B_l, T_l = tokens.shape
            n_sp = mesh.shape[sp] if sp else 1
            if T_l * n_sp > cfg.max_len:
                # shapes are static: fail at trace time, not by the silent
                # index clamp a jit gather would apply past the table end
                raise ValueError(
                    f"sequence length {T_l * n_sp} exceeds max_len "
                    f"{cfg.max_len}")
            sp_idx = lax.axis_index(sp) if sp else 0
            positions = sp_idx * T_l + jnp.arange(T_l)
            x = params["embed"][tokens] + params["pos_embed"][positions]

            R = grad_accum_rounds
            if B_l % (R * n_microbatches):
                raise ValueError(
                    f"local batch {B_l} not divisible by rounds*microbatches "
                    f"{R}x{n_microbatches}")
            x_r = x.reshape((R, B_l // R) + x.shape[1:])
            tgt_r = targets.reshape((R, B_l // R) + targets.shape[1:])

            def round_fn(carry, xs):
                xr, tr = xs
                if pp:
                    h, aux = pipeline_train_apply(
                        stage_fn, stage_p, xr, pp, n_microbatches,
                        schedule=schedule, remat=remat,
                        n_chunks=(v if schedule == "interleaved"
                                  else None),
                        offload=offload)
                else:
                    # no pp axis: same microbatch chunking, plain scan —
                    # this IS the grad-accumulation baseline. With chunked
                    # (v, ...) params every virtual stage still runs, in
                    # vs order (S=1, so vs == c).
                    mb = xr.shape[0] // n_microbatches
                    xm = xr.reshape((n_microbatches, mb) + xr.shape[1:])
                    if v > 1:
                        def all_chunks(sp_, hh):
                            aa = jnp.float32(0)
                            for c in range(v):
                                chunk = {k: leaf[c]
                                         for k, leaf in sp_.items()}
                                hh, a = stage_fn(chunk, hh)
                                aa = aa + a
                            # per chunk-visit mean, matching the pipeline's
                            # psum/(V*M) normalization
                            return hh, aa / v
                        mb_stage = remat_stage_fn(all_chunks, remat,
                                                  offload=offload)
                    else:
                        mb_stage = remat_stage_fn(stage_fn, remat,
                                                  offload=offload)

                    def mb_fn(_, xmb):
                        hh, aa = mb_stage(stage_p, xmb)
                        return None, (hh, aa)
                    _, (hs, aas) = lax.scan(mb_fn, None, xm)
                    h = hs.reshape(xr.shape)
                    aux = jnp.mean(aas)
                h = model._ln(h, params["lnf_g"], params["lnf_b"])
                logits = (h @ params["embed"].T).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(logp, tr[..., None],
                                           axis=-1)[..., 0]
                loss_r = jnp.mean(nll) + cfg.aux_weight * aux
                return carry + loss_r, None

            total, _ = lax.scan(round_fn, jnp.float32(0), (x_r, tgt_r))
            loss = total / R
            for ax in mesh_axes:
                loss = lax.pmean(loss, ax)
            return loss

        loss_fn = shard_map(
            local_loss, mesh,
            in_specs=(specs, data_spec, data_spec), out_specs=P())

        from ..parallel.train import _make_update_rule
        _, adam_rule = _make_update_rule("adam", lr, 0.0, 0.0, {})

        def step(params, opt_state, tokens, targets, step_i):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                      targets)
            new_params, new_opt = {}, {}
            t = step_i + 1
            for k, g in grads.items():
                w32, new_opt[k] = adam_rule(params[k].astype(jnp.float32),
                                            g.astype(jnp.float32),
                                            opt_state[k], t)
                new_params[k] = w32.astype(params[k].dtype)
            return new_params, new_opt, loss

        shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}
        axes_sig = "x".join(f"{a}{mesh.shape[a]}" for a in mesh.axis_names)
        jit_key = (f"trainstep:composed:{axes_sig}:{schedule}:"
                   f"remat-{remat}:M{n_microbatches}:R{grad_accum_rounds}")
        # suffixes only when non-default, so pre-existing keys (and the
        # shardlint waivers annotated on them) stay byte-stable
        if v > 1:
            jit_key += f":v{v}"
        if offload:
            jit_key += ":offload"
        pstats = schedule_stats(schedule, S, n_microbatches,
                                n_chunks=(v if schedule == "interleaved"
                                          else None))
        bubble = pstats["bubble_fraction"] if pp else 0.0

        from .. import compile_cache as _cc
        from .. import profiler as _prof
        from .. import shardlint as _sl
        from ..parallel.train import default_compiler_options
        # grads stay positionally inside the program (value_and_grad is
        # fused into the step), so params/opt_state are the only donation
        # candidates; data/step args are neutral. The all-gather budget
        # covers the param gathers XLA materializes for the replicated
        # embed/final-LN tensors used on every (round, microbatch) visit.
        _sl.annotate(jit_key,
                     arg_roles={0: "params", 1: "opt_state", 2: "data",
                                3: "data", 4: "step"},
                     declared_bf16=(jnp.dtype(cfg.dtype) == jnp.bfloat16),
                     allgather_budget=16)
        # donation only where the backend actually aliases buffers — the
        # SL03 true positive the corpus self-run caught here, same gate
        # as TrainStep and the fused optimizer
        from ..ops.optimizer_ops import _donation_supported
        cached = _cc.cached_jit(
            jit_key, step,
            in_shardings=(shardings,
                          {k: (shardings[k], shardings[k]) for k in specs},
                          NamedSharding(mesh, data_spec),
                          NamedSharding(mesh, data_spec), None),
            donate_argnums=(0, 1) if _donation_supported() else (),
            compiler_options=default_compiler_options())

        _off_counter = []

        def _book_offload(tokens):
            # analytic D2H traffic: every (stage, chunk, round, microbatch)
            # visit parks its stage-input residual on pinned host exactly
            # once, so one step moves S*v copies of the full (B, T, D)
            # activation regardless of the M/R chunking
            if not (offload and _prof.is_running()):
                return
            if not _off_counter:
                _off_counter.append(_prof.Counter(name="d2h_bytes"))
            B_, T_ = tokens.shape[0], tokens.shape[1]
            _off_counter[0].increment(
                S * v * B_ * T_ * cfg.d_model
                * jnp.dtype(cfg.dtype).itemsize)

        def jit_step(params, opt_state, tokens, targets, step_i):
            _book_offload(tokens)
            if not (pp and _prof.attribution_enabled()):
                return cached(params, opt_state, tokens, targets, step_i)
            import time
            t0 = time.perf_counter()
            out = cached(params, opt_state, tokens, targets, step_i)
            jax.block_until_ready(out[2])
            dur_ms = (time.perf_counter() - t0) * 1e3
            # one XLA program = one opaque span on the device timeline;
            # the schedule grid says what share of the stage-ticks inside
            # it are structurally idle, so the step's wall time is split
            # by that fraction rather than by (unobservable) per-stage
            # device spans
            _prof.observe_phase("compute", dur_ms * (1.0 - bubble), t0=t0)
            _prof.observe_phase("pp_bubble", dur_ms * bubble, t0=t0)
            _prof.phase_step_end()
            return out

        jit_step._cached = cached
        jit_step.jit_key = jit_key
        jit_step.schedule = schedule
        jit_step.remat = remat
        jit_step.n_chunks = v
        jit_step.offload = offload
        jit_step.bubble_fraction = bubble
        jit_step.schedule_stats = pstats

        def shard_params(params):
            return {k: jax.device_put(jnp.asarray(v).copy(), shardings[k])
                    for k, v in params.items()}

        def init_opt(params):
            return {k: (jnp.zeros(v.shape, jnp.float32),
                        jnp.zeros(v.shape, jnp.float32))
                    for k, v in params.items()}

        return jit_step, shard_params, init_opt

    # -- single-device oracle ----------------------------------------------
    def reference_loss(self, params, tokens, targets, *, dp_groups=1,
                       sp_shards=1, n_microbatches=2, grad_accum_rounds=1):
        """Dense single-device forward computing the SAME loss the composed
        step computes, including the MoE gating GROUPS (gating capacity is
        per (dp shard, round, microbatch, sp shard) token group in the
        composed run; the oracle reproduces that chunking so dispatch
        decisions — and with dropless capacity, the loss — match)."""
        cfg = self.cfg
        wq = params["b0_wq"]
        # (v, S, ...)-stacked block tensors mark a chunked (interleaved)
        # layout; execution order is virtual-stage order vs = c*S + s
        if wq.ndim == 4:
            v_chunks, S = wq.shape[0], wq.shape[1]
        else:
            v_chunks, S = 1, wq.shape[0]
        lps = cfg.n_layers // (S * v_chunks)
        B, T = tokens.shape
        x = params["embed"][tokens] + params["pos_embed"][jnp.arange(T)]

        def run_blocks(xg):
            aux_total = jnp.float32(0)
            for vs in range(v_chunks * S):
                c, s = vs // S, vs % S
                p = {k: ((v[c, s] if v_chunks > 1 else v[s])
                         if v.ndim and k.startswith("b") else v)
                     for k, v in params.items()}
                for j in range(lps):
                    kind = self._ffn_kind(j)
                    Bg, Tg, D = xg.shape
                    h = self._ln(xg, p[f"b{j}_ln1_g"], p[f"b{j}_ln1_b"])
                    hd = D // cfg.n_heads
                    q = (h @ p[f"b{j}_wq"]).reshape(Bg, Tg, -1, hd)
                    k_ = (h @ p[f"b{j}_wk"]).reshape(Bg, Tg, -1, hd)
                    v_ = (h @ p[f"b{j}_wv"]).reshape(Bg, Tg, -1, hd)
                    attn = attention_reference(q, k_, v_, causal=True)
                    xg = xg + attn.reshape(Bg, Tg, D) @ p[f"b{j}_wo"]
                    h = self._ln(xg, p[f"b{j}_ln2_g"], p[f"b{j}_ln2_b"])
                    if kind == "moe":
                        # chunk into the composed run's gating groups: the
                        # sp axis splits the SEQUENCE of each microbatch
                        flat_groups = []
                        auxs = []
                        Tl = Tg // sp_shards
                        for si in range(sp_shards):
                            seg = h[:, si * Tl:(si + 1) * Tl, :]
                            yseg, aux = moe_apply(
                                seg.reshape(Bg * Tl, D),
                                {"wg": p[f"b{j}_wg"], "w1": p[f"b{j}_w1"],
                                 "w2": p[f"b{j}_w2"]},
                                capacity_factor=cfg.capacity_factor)
                            flat_groups.append(yseg.reshape(Bg, Tl, D))
                            auxs.append(aux)
                        y = jnp.concatenate(flat_groups, axis=1)
                        aux_total = aux_total + jnp.mean(jnp.stack(auxs))
                    else:
                        y = jax.nn.gelu(h @ p[f"b{j}_w_in"]) @ \
                            p[f"b{j}_w_out"]
                    xg = xg + y
            return xg, aux_total

        # reproduce the (dp, round, microbatch) batch chunking
        per_dp = B // dp_groups
        losses = []
        for g in range(dp_groups):
            xg_all = x[g * per_dp:(g + 1) * per_dp]
            tg_all = targets[g * per_dp:(g + 1) * per_dp]
            per_round = per_dp // grad_accum_rounds
            round_losses = []
            for r in range(grad_accum_rounds):
                xr = xg_all[r * per_round:(r + 1) * per_round]
                tr = tg_all[r * per_round:(r + 1) * per_round]
                mb = per_round // n_microbatches
                aux_sum = jnp.float32(0)
                outs = []
                for m in range(n_microbatches):
                    xm = xr[m * mb:(m + 1) * mb]
                    o, aux = run_blocks(xm)
                    outs.append(o)
                    aux_sum = aux_sum + aux
                h = jnp.concatenate(outs)
                h = self._ln(h, params["lnf_g"], params["lnf_b"])
                logits = (h @ params["embed"].T).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(logp, tr[..., None],
                                           axis=-1)[..., 0]
                # the composed aux is meaned over the S * v * M real
                # (stage, chunk, microbatch) visits; aux_sum here has
                # summed all blocks over all M microbatches
                aux_mean = aux_sum / (S * v_chunks * n_microbatches)
                round_losses.append(jnp.mean(nll) +
                                    cfg.aux_weight * aux_mean)
            losses.append(jnp.mean(jnp.stack(round_losses)))
        return jnp.mean(jnp.stack(losses))
