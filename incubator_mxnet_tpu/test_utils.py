"""Shared test utilities, shipped in the package so all frontends/CI reuse it.

Reference: python/mxnet/test_utils.py (2,212 LoC): assert_almost_equal:501,
check_numeric_gradient:872, check_symbolic_forward:1015/backward:1097,
check_consistency:1304, rand_ndarray, same:480, default_context().
"""
from __future__ import annotations

import numpy as _np

from . import autograd, nd
from .context import Context, cpu, current_context

__all__ = ["default_context", "assert_almost_equal", "same", "rand_ndarray",
           "rand_shape_2d", "rand_shape_3d", "check_numeric_gradient",
           "check_consistency", "check_symbolic_forward",
           "check_symbolic_backward", "almost_equal"]

_default_ctx = None


def default_context() -> Context:
    return _default_ctx or current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def _dtype_tol(dtype):
    d = _np.dtype(dtype) if "bfloat16" not in str(dtype) else None
    if d is None or d == _np.float16:
        return 1e-2, 1e-2
    if d == _np.float64:
        return 1e-7, 1e-9
    return 1e-4, 1e-5


def same(a, b):
    return _np.array_equal(_to_np(a), _to_np(b))


def _to_np(a):
    return a.asnumpy() if isinstance(a, nd.NDArray) else _np.asarray(a)


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _to_np(a), _to_np(b)
    drt, dat = _dtype_tol(a.dtype)
    return _np.allclose(a, b, rtol=rtol or drt, atol=atol or dat)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    """dtype-aware tolerance compare (reference test_utils.py:501)."""
    a, b = _to_np(a), _to_np(b)
    drt, dat = _dtype_tol(a.dtype)
    _np.testing.assert_allclose(a, b, rtol=rtol if rtol is not None else drt,
                                atol=atol if atol is not None else dat,
                                err_msg=f"{names[0]} != {names[1]}")


def rand_ndarray(shape, dtype="float32", ctx=None, scale=1.0):
    return nd.array(_np.random.uniform(-scale, scale, shape).astype(dtype), ctx=ctx)


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(_np.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(_np.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference gradient check against autograd
    (reference test_utils.py:872 check_numeric_gradient)."""
    arrays = [nd.array(x) if not isinstance(x, nd.NDArray) else x for x in inputs]
    for a in arrays:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrays)
        if isinstance(out, (list, tuple)):
            out = sum((o.sum() for o in out[1:]), out[0].sum())
        elif out.size != 1:
            out = out.sum()
    out.backward()
    analytic = [a.grad.asnumpy().copy() for a in arrays]

    for ai, a in enumerate(arrays):
        base = a.asnumpy().astype(_np.float64)
        num = _np.zeros_like(base)
        flat = base.reshape(-1)
        numf = num.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            with autograd.pause():
                fp = _scalar_eval(fn, arrays, ai, base)
            flat[i] = orig - eps
            with autograd.pause():
                fm = _scalar_eval(fn, arrays, ai, base)
            flat[i] = orig
            numf[i] = (fp - fm) / (2 * eps)
        _np.testing.assert_allclose(analytic[ai], num, rtol=rtol, atol=atol,
                                    err_msg=f"gradient mismatch on input {ai}")


def _scalar_eval(fn, arrays, ai, perturbed):
    saved = arrays[ai]._data
    arrays[ai]._data = nd.array(perturbed.astype(_np.float32))._data
    try:
        out = fn(*arrays)
        if isinstance(out, (list, tuple)):
            return float(sum(float(o.sum().asscalar()) for o in out))
        return float(out.sum().asscalar())
    finally:
        arrays[ai]._data = saved


def check_consistency(fn, inputs, ctx_list=None, dtype_list=None, rtol=None,
                      atol=None, ref_dtype="float32"):
    """Run fn across a (context x dtype) matrix and compare every run
    against the highest-precision one — the reference's cross-device
    oracle (test_utils.py:1304), which validates GPU kernels against CPU
    there and bf16/f16 TPU paths against fp32 here.

    Each entry of the matrix gets dtype-aware tolerances unless rtol/atol
    are forced. Returns {(ctx, dtype): np output}.
    """
    ctx_list = ctx_list or [cpu(0)]
    dtype_list = dtype_list or [ref_dtype]
    results = {}
    for ctx in ctx_list:
        for dt in dtype_list:
            arrs = [nd.array(_np.asarray(x), ctx=ctx).astype(dt)
                    for x in inputs]
            out = fn(*arrs)
            out = out[0] if isinstance(out, (list, tuple)) else out
            results[(str(ctx), str(dt))] = _to_np(out)
    ref_key = next((k for k in results if k[1] == str(ref_dtype)),
                   next(iter(results)))
    ref = results[ref_key].astype(_np.float64)
    for key, o in results.items():
        if key == ref_key:
            continue
        drt, dat = _dtype_tol(o.dtype)
        _np.testing.assert_allclose(
            o.astype(_np.float64), ref,
            rtol=rtol if rtol is not None else drt,
            atol=atol if atol is not None else dat,
            err_msg=f"{key} inconsistent with {ref_key}")
    return results


def _parse_location(sym, location, dtype):
    """list/dict of arrays -> ordered {arg_name: NDArray}
    (reference test_utils.py:178 _parse_location)."""
    arg_names = sym.list_arguments()
    if isinstance(location, dict):
        unknown = set(location) - set(arg_names)
        if unknown:
            raise ValueError(f"location has keys {sorted(unknown)} not in "
                             f"list_arguments()={arg_names}")
        pairs = [(n, location[n]) for n in arg_names if n in location]
    else:
        if len(location) != len(arg_names):
            raise ValueError(f"expected {len(arg_names)} location entries "
                             f"({arg_names}), got {len(location)}")
        pairs = list(zip(arg_names, location))
    out = {}
    for n, v in pairs:
        if not isinstance(v, nd.NDArray):
            v = nd.array(_np.asarray(v, dtype=dtype))
        out[n] = v
    return out


def check_symbolic_forward(sym, location, expected, rtol=None, atol=None,
                           aux_states=None, ctx=None, dtype="float32"):
    """Bind `sym`, run one inference forward, compare each output against
    `expected` (reference test_utils.py:1015). Returns the outputs as
    numpy arrays so callers can chain further checks."""
    ctx = ctx or default_context()
    args = _parse_location(sym, location, dtype)
    if aux_states is not None and not isinstance(aux_states, dict):
        aux_states = dict(zip(sym.list_auxiliary_states(), aux_states))
    exe = sym.bind(ctx=ctx, args=args, grad_req="null",
                   aux_states={k: nd.array(_np.asarray(v, dtype=dtype))
                               if not isinstance(v, nd.NDArray) else v
                               for k, v in (aux_states or {}).items()})
    outputs = [o.asnumpy() for o in exe.forward(is_train=False)]
    if isinstance(expected, dict):
        expected = [expected[n] for n in sym.list_outputs()]
    if len(expected) != len(outputs):
        raise ValueError(f"symbol has {len(outputs)} outputs, expected "
                         f"list has {len(expected)}")
    for i, (got, want) in enumerate(zip(outputs, expected)):
        assert_almost_equal(got, _to_np(want), rtol=rtol, atol=atol,
                            names=(f"output[{i}]", f"expected[{i}]"))
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=None,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, dtype="float32"):
    """Bind `sym`, run forward + backward with `out_grads` as head
    gradients, compare argument gradients against `expected`
    (reference test_utils.py:1097). `expected` may be a dict keyed by
    argument name (args with grad_req null are not checked) or a full
    list. Returns {arg_name: grad ndarray-as-numpy}."""
    ctx = ctx or default_context()
    args = _parse_location(sym, location, dtype)
    arg_names = sym.list_arguments()
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(arg_names, expected))
    if isinstance(grad_req, str):
        grad_req = {n: grad_req for n in arg_names}
    elif isinstance(grad_req, (list, tuple)):
        grad_req = dict(zip(arg_names, grad_req))
    if aux_states is not None and not isinstance(aux_states, dict):
        aux_states = dict(zip(sym.list_auxiliary_states(), aux_states))
    exe = sym.bind(ctx=ctx, args=args, grad_req=grad_req,
                   aux_states={k: nd.array(_np.asarray(v, dtype=dtype))
                               if not isinstance(v, nd.NDArray) else v
                               for k, v in (aux_states or {}).items()})
    exe.forward(is_train=True)
    if out_grads is not None:
        if isinstance(out_grads, dict):
            out_grads = [out_grads[n] for n in sym.list_outputs()]
        if not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
        out_grads = [g if isinstance(g, nd.NDArray)
                     else nd.array(_np.asarray(g, dtype=dtype))
                     for g in out_grads]
    exe.backward(out_grads)
    grads = {n: g.asnumpy() for n, g in exe.grad_dict.items()}
    for name, want in expected.items():
        if grad_req.get(name, "write") == "null":
            continue
        if name not in grads:
            raise ValueError(f"no gradient produced for argument {name!r}")
        assert_almost_equal(grads[name], _to_np(want), rtol=rtol, atol=atol,
                            names=(f"grad[{name}]", f"expected[{name}]"))
    return grads
