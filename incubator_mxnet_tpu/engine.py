"""Engine control surface (reference python/mxnet/engine.py, 75 LoC).

The reference exposes `bulk(size)` to batch engine ops and reduce dispatch
overhead (MXEngineSetBulkSize). XLA's async runtime already pipelines
dispatch, so the closest analog of op bulking here is the Trainer's
aggregated optimizer step: a nonzero bulk size overrides
`MXNET_OPTIMIZER_AGGREGATION_SIZE` as the per-bucket parameter count
(gluon/trainer.py), so reference code wrapping its update loop in
`engine.bulk(n)` actually changes batching behavior. `set_bulk_size`
returns the previous value like the C API did, and `bulk(size)` restores
it on exit.
"""
from __future__ import annotations

from contextlib import contextmanager

__all__ = ["bulk", "bulk_size", "set_bulk_size"]

_bulk_size = 0


def set_bulk_size(size):
    """Reference engine.py set_bulk_size -> MXEngineSetBulkSize."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


def bulk_size():
    """Current bulk size; 0 means 'unset' (the Trainer then falls back to
    MXNET_OPTIMIZER_AGGREGATION_SIZE)."""
    return _bulk_size


@contextmanager
def bulk(size):
    """Reference engine.py bulk(size) context manager."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
