"""Training callbacks (reference python/mxnet/callback.py, 222 LoC).

Same surface: `Speedometer` throughput logging in the reference's
speedometer format (consumed by tools/parse_log.py-style scripts),
`do_checkpoint` epoch-end saving, `LogValidationMetricsCallback`.
"""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "LogValidationMetricsCallback", "module_checkpoint"]


def do_checkpoint(prefix, period=1):
    """Epoch-end callback: save symbol+params every `period` epochs
    (reference callback.py do_checkpoint -> model.save_checkpoint)."""
    from .model import save_checkpoint

    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset_local()

    return _callback


class Speedometer:
    """Logs samples/sec every `frequent` batches (reference callback.py:132)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                try:
                    speed = self.frequent * self.batch_size / \
                        (time.time() - self.tic)
                except ZeroDivisionError:
                    speed = float("inf")
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset_local()
                    msg = "Epoch[%d] Batch [%d-%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count - self.frequent,
                                 count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class LogValidationMetricsCallback:
    """Epoch-end eval logging (reference callback.py:222)."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name, value)
