from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter, MXDataIter, ImageRecordIter)

__all__ = ["DataBatch", "DataDesc", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MXDataIter", "ImageRecordIter"]
