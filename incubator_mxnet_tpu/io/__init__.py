from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter, MXDataIter, ImageRecordIter,
                 MNISTIter, LibSVMIter)
from .prefetch import (DevicePrefetcher, HostOffloader,
                       prefetch_to_device)

__all__ = ["DataBatch", "DataDesc", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MXDataIter", "ImageRecordIter",
           "MNISTIter", "LibSVMIter", "DevicePrefetcher", "HostOffloader",
           "prefetch_to_device"]
