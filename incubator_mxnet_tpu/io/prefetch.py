"""Device-side input pipeline: double-buffered host->HBM prefetch.

Reference: src/io/iter_prefetcher.h:47 ``PrefetcherIter`` — a
dmlc::ThreadedIter double-buffer hiding batch N+1's decode+copy behind
batch N's compute. The reference's buffer stops at host memory: the
NDArray->device copy still serializes with the step. Here the background
stage issues the host->HBM transfer itself — ``jax.device_put`` is async
(it returns immediately with a future-backed Array), so batch N+1's DMA
overlaps batch N's XLA program. Given a mesh, placement uses a
``NamedSharding`` over the data axis, so multichip consumers (TrainStep,
``parallel.train.shard_batch`` users) receive pre-placed shards and never
pay a second device_put.

Telemetry (the data-stall diagnosis surface): every consumer get publishes

- ``input_wait_ms_per_step`` — time the step blocked waiting for input
  (0 in steady state means the pipeline keeps the chip fed)
- ``prefetch_depth``        — batches ready in the buffer after the get
  (pinned at 0 means the run is input-bound)
- ``h2d_bytes``             — cumulative bytes staged to the device

through the profiler counter registry, so a stalled run is diagnosable
from ``profiler.dumps()`` or the ``/metrics`` Prometheus scrape alone.
"""
from __future__ import annotations

import queue as _queue_mod
import threading
import time

import numpy as _np

from ..base import MXNetError

__all__ = ["DevicePrefetcher", "HostOffloader", "prefetch_to_device"]


def prefetch_to_device(iterator, size=2, mesh=None, axis="dp", device=None,
                       skip_batches=0):
    """Wrap a host batch iterator in a background device-placement stage.

    iterator: anything iterable yielding batches — NDArrays, (data, label)
        tuples/lists, dicts, numpy arrays, or io.DataBatch objects. Array
        leaves are placed on device asynchronously; non-array leaves pass
        through untouched.
    size:     queue depth (2 = classic double buffering).
    skip_batches: discard this many source batches on the worker thread
        WITHOUT device placement — the mid-epoch-exact resume fast-forward
        (fault.AsyncCheckpointManager stores the consumed-batch cursor;
        passing it here replays an epoch from the exact next batch). The
        skipped batches still advance :attr:`cursor`.
    mesh/axis: place leaves with NamedSharding(mesh, P(axis)) — pre-sharded
        input for SPMD consumers (TrainStep skips its own device_put on
        shards that already carry this sharding).
    device:   explicit jax device target (mutually exclusive with mesh).
        With neither, numpy leaves go to the default device and
        already-committed arrays are left in place (their transfer was
        issued on the prefetch thread, which is the point).

    Returns a :class:`DevicePrefetcher` — an iterator that preserves the
    source order and values bit-for-bit, supports early abandonment via
    ``close()`` (the source iterator's cleanup runs on the worker thread,
    so a generator source's ``finally`` — e.g. the DataLoader shm drain —
    still executes), and publishes data-stall counters to the profiler.
    """
    return DevicePrefetcher(iterator, size=size, mesh=mesh, axis=axis,
                            device=device, skip_batches=skip_batches)


class DevicePrefetcher:
    """Single background thread + bounded FIFO queue: the host stages of
    the source iterator (decode, batchify, shm copy-out) AND the H2D issue
    run off the consumer thread; order is preserved by construction."""

    def __init__(self, iterator, size=2, mesh=None, axis="dp", device=None,
                 skip_batches=0):
        if size < 1:
            raise MXNetError("prefetch size must be >= 1")
        if mesh is not None and device is not None:
            raise MXNetError("mesh and device are mutually exclusive")
        if skip_batches < 0:
            raise MXNetError("skip_batches must be >= 0")
        self._src = iter(iterator)
        self._skip = int(skip_batches)
        # source batches consumed, INCLUDING skipped ones: the data-iterator
        # position a checkpoint records for mid-epoch-exact resume
        self.cursor = int(skip_batches)
        self._sharding = None
        self._device = device
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._sharding = NamedSharding(mesh, P(axis))
        self.size = size
        self._queue = _queue_mod.Queue(maxsize=size)
        self._stop = threading.Event()
        self._done = False
        # consumer-side telemetry: written only by the consuming thread
        # (the worker communicates through the queue alone), so no lock
        self.batches = 0
        self.bytes_total = 0
        self.last_wait_ms = 0.0
        self.wait_ms_total = 0.0
        self._counters = None
        self._thread = threading.Thread(target=self._worker,
                                        name="mxtpu-device-prefetch",
                                        daemon=True)
        self._thread.start()

    # -- producer ----------------------------------------------------------
    def _worker(self):
        src = self._src
        try:
            # resume fast-forward: burn the already-consumed prefix off the
            # worker thread, no placement cost, before the first real batch
            for _ in range(self._skip):
                if self._stop.is_set():
                    return
                try:
                    next(src)
                except StopIteration:
                    self._offer(("done", None, 0))
                    return
            while not self._stop.is_set():
                try:
                    batch = next(src)
                except StopIteration:
                    self._offer(("done", None, 0))
                    return
                placed, nbytes = self._place(batch)
                if not self._offer(("ok", placed, nbytes)):
                    return                      # closed while queue full
        except BaseException as e:              # noqa: BLE001 — re-raised
            self._offer(("err", e, 0))          # in the consumer
        finally:
            # the worker owns the source: closing it HERE runs a generator
            # source's finally blocks (the DataLoader shm drain) on the
            # thread the generator actually executed on
            close = getattr(src, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:               # noqa: BLE001
                    pass

    def _offer(self, item):
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except _queue_mod.Full:
                continue
        return False

    # -- placement ---------------------------------------------------------
    def _place(self, batch):
        nbytes = [0]
        return self._place_tree(batch, nbytes), nbytes[0]

    def _place_tree(self, x, nbytes):
        from ..ndarray.ndarray import NDArray
        from .io import DataBatch
        if type(x) is NDArray:
            return NDArray(self._place_leaf(x._data, nbytes))
        if isinstance(x, NDArray):
            return x        # sparse containers: multi-buffer, pass through
        if isinstance(x, DataBatch):
            out = DataBatch(
                data=self._place_tree(x.data, nbytes),
                label=self._place_tree(x.label, nbytes),
                pad=x.pad, index=x.index, bucket_key=x.bucket_key,
                provide_data=x.provide_data, provide_label=x.provide_label)
            return out
        if isinstance(x, (tuple, list)):
            return type(x)(self._place_tree(v, nbytes) for v in x)
        if isinstance(x, dict):
            return {k: self._place_tree(v, nbytes) for k, v in x.items()}
        if isinstance(x, _np.ndarray) or hasattr(x, "devices"):
            return self._place_leaf(x, nbytes)
        return x

    def _place_leaf(self, a, nbytes):
        import jax
        import jax.numpy as jnp
        if self._sharding is not None:
            placed = jax.device_put(a, self._sharding)
        elif self._device is not None:
            placed = jax.device_put(a, self._device)
        elif hasattr(a, "devices"):
            # already device-resident: its H2D was issued by whatever
            # constructed it — which ran on THIS thread, inside next(src)
            placed = a
        else:
            placed = jnp.asarray(a)
        try:
            nbytes[0] += int(placed.nbytes)
        except (TypeError, AttributeError):
            pass
        return placed

    # -- consumer ----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                kind, payload, nbytes = self._queue.get(timeout=1.0)
                break
            except _queue_mod.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    self._done = True
                    raise MXNetError(
                        "device prefetch worker died without a sentinel")
        wait_ms = (time.perf_counter() - t0) * 1e3
        if kind != "ok":
            self._done = True
            self._thread.join(timeout=5)
            if kind == "err":
                raise payload
            raise StopIteration
        self.batches += 1
        self.cursor += 1
        self.bytes_total += nbytes
        self.last_wait_ms = wait_ms
        self.wait_ms_total += wait_ms
        self._publish(wait_ms)
        return payload

    def _publish(self, wait_ms):
        from .. import profiler
        if not profiler.is_running():
            return
        if self._counters is None:
            self._counters = (
                profiler.Counter(name="input_wait_ms_per_step"),
                profiler.Counter(name="prefetch_depth"),
                profiler.Counter(name="h2d_bytes"))
        self._counters[0].set_value(round(wait_ms, 3))
        self._counters[1].set_value(self._queue.qsize())
        self._counters[2].set_value(self.bytes_total)

    def stats(self):
        """Always-readable snapshot (the counters above require a running
        profiler; tests and bench read this directly)."""
        return {"batches": self.batches, "h2d_bytes": self.bytes_total,
                "last_wait_ms": self.last_wait_ms,
                "wait_ms_total": self.wait_ms_total,
                "depth": self._queue.qsize(), "size": self.size,
                "cursor": self.cursor}

    def state(self):
        """Checkpointable position: pass ``state()['cursor']`` back as
        ``skip_batches`` over the same source to resume mid-epoch exactly
        (no skipped, no repeated batches)."""
        return {"cursor": self.cursor}

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        """Stop the worker and drop buffered batches. Safe to call twice.
        Early abandonment (break out of the consuming loop) MUST end here
        (or via GC) so the source's cleanup runs — for the DataLoader shm
        protocol that is what unlinks in-flight segments."""
        self._done = True
        self._stop.set()
        # drain so a worker blocked on a full queue observes the stop
        self._drain()
        if self._thread.is_alive():
            self._thread.join(timeout=10)
        self._drain()       # anything offered between drain and join

    def _drain(self):
        try:
            while True:
                self._queue.get_nowait()
        except _queue_mod.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:                       # noqa: BLE001 — interpreter
            pass                                # shutdown: queue/thread gone


class HostOffloader:
    """The DevicePrefetcher's machinery run in REVERSE: a bounded window of
    async device->host copies of live activations, prefetched BACK to the
    device ahead of their consumer. Reference: the MXNet dependency engine
    hiding D2H/H2D under compute via dependency-ordered async copies —
    here ``jax.device_put`` to a host ``memory_kind`` is the async copy and
    the bounded window is the double buffer.

    ``put(key, a)`` issues the D2H and returns immediately; when the
    in-flight window is full it first BLOCKS on the oldest transfer (that
    wait is the ``offload_wait_ms_per_step`` stall the counters surface —
    0 in steady state means the copies hide under compute). ``prefetch``
    issues the H2D back without blocking; ``get`` returns the
    device-resident array, waiting only if the prefetch hasn't landed.
    Round trips are bit-identical by construction (same buffer, moved).

    Telemetry, through the same profiler counter registry as the input
    pipeline (``profiler.dumps()`` / the ``/metrics`` scrape):

    - ``d2h_bytes``               — cumulative bytes parked on the host
    - ``offload_wait_ms_per_step`` — consumer time blocked on the window

    On backends without addressable host memory spaces the offloader
    degrades to an on-device ring (``host_backed`` False): the window
    accounting and telemetry stay live, the copies become no-ops.
    """

    def __init__(self, window=2):
        if window < 1:
            raise MXNetError("offload window must be >= 1")
        self.window = window
        self._host = {}           # key -> host-resident array
        self._back = {}           # key -> device-put-back array (prefetch)
        self._order = []          # FIFO of in-flight D2H keys
        self._shardings = {}      # key -> original device sharding
        self.d2h_bytes = 0
        self.h2d_bytes = 0
        self.last_wait_ms = 0.0
        self.wait_ms_total = 0.0
        self.puts = 0
        self._counters = None
        self._host_kind = self._probe_host_kind()

    @staticmethod
    def _probe_host_kind():
        import jax
        try:
            kinds = {m.kind for d in jax.local_devices()
                     for m in d.addressable_memories()}
        except Exception:                       # noqa: BLE001 — old jax
            return None
        for kind in ("pinned_host", "unpinned_host"):
            if kind in kinds:
                return kind
        return None

    @property
    def host_backed(self):
        return self._host_kind is not None

    # -- D2H ---------------------------------------------------------------
    def put(self, key, a):
        """Issue an async D2H of `a`; blocks only when the window is full
        (on the OLDEST in-flight transfer, double-buffer style)."""
        import jax
        if key in self._host or key in self._back:
            raise MXNetError(f"offload key {key!r} already live")
        wait_ms = 0.0
        while len(self._order) >= self.window:
            oldest = self._order.pop(0)
            t0 = time.perf_counter()
            jax.block_until_ready(self._host[oldest])
            wait_ms += (time.perf_counter() - t0) * 1e3
        self._shardings[key] = getattr(a, "sharding", None)
        if self._host_kind is not None and self._shardings[key] is not None:
            dst = self._shardings[key].with_memory_kind(self._host_kind)
            self._host[key] = jax.device_put(a, dst)
        else:
            self._host[key] = a                 # degraded: on-device ring
        self._order.append(key)
        try:
            self.d2h_bytes += int(a.nbytes)
        except (TypeError, AttributeError):
            pass
        self.puts += 1
        self.last_wait_ms = wait_ms
        self.wait_ms_total += wait_ms
        self._publish(wait_ms)
        return self._host[key]

    # -- H2D ---------------------------------------------------------------
    def prefetch(self, key):
        """Issue the async H2D back to the original sharding; returns
        immediately (call one backward-tick ahead of `get`)."""
        import jax
        if key in self._back:
            return
        if key not in self._host:
            raise MXNetError(f"offload key {key!r} not resident")
        a = self._host.pop(key)
        if key in self._order:
            self._order.remove(key)
        sh = self._shardings.pop(key)
        if self._host_kind is not None and sh is not None:
            a = jax.device_put(a, sh)
        self._back[key] = a
        try:
            self.h2d_bytes += int(a.nbytes)
        except (TypeError, AttributeError):
            pass

    def get(self, key):
        """Device-resident array for `key`; issues the H2D itself if no
        prefetch ran (then the wait is the transfer, which is the stall
        the schedule is supposed to hide)."""
        if key not in self._back:
            self.prefetch(key)
        return self._back.pop(key)

    # -- telemetry ---------------------------------------------------------
    def _publish(self, wait_ms):
        from .. import profiler
        if not profiler.is_running():
            return
        if self._counters is None:
            self._counters = (
                profiler.Counter(name="d2h_bytes"),
                profiler.Counter(name="offload_wait_ms_per_step"))
        self._counters[0].set_value(self.d2h_bytes)
        self._counters[1].set_value(round(wait_ms, 3))

    def stats(self):
        """Always-readable snapshot (counters need a running profiler)."""
        return {"puts": self.puts, "d2h_bytes": self.d2h_bytes,
                "h2d_bytes": self.h2d_bytes,
                "last_wait_ms": self.last_wait_ms,
                "wait_ms_total": self.wait_ms_total,
                "resident": len(self._host) + len(self._back),
                "in_flight": len(self._order), "window": self.window,
                "host_backed": self.host_backed}
