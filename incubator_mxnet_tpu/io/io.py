"""Data iterators.

Reference: python/mxnet/io/io.py (1,097 LoC): `DataIter:180`,
`NDArrayIter:491` (pad/shuffle/last-batch handling), `MXDataIter:790`
(C++-registered iterators), DataBatch/DataDesc; C++ pipeline src/io/
(RecordIO/image decode/prefetch — see recordio.py and image/ here).

TPU-native notes: iterators yield host-side batches; the device transfer is
the first op that touches the NDArray (jax device_put), which overlaps with
compute thanks to XLA async dispatch — the reference needed an explicit
PrefetcherIter double-buffer (iter_prefetcher.h:47) for the same effect, and a
threaded PrefetchingIter is still provided for heavy host-side pipelines.
"""
from __future__ import annotations

import collections
import threading

import numpy as _np

from .. import nd
from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MXDataIter", "ImageRecordIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape", "dtype",
                                                   "layout"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """Reference io.py DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in self.data] if self.data else []
        return f"DataBatch: data shapes {shapes}"


class DataIter:
    """Reference io.py:180."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, NDArray) (reference io.py _init_data)."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (_np.ndarray, nd.NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if not isinstance(v, nd.NDArray):
            v = nd.array(_np.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:491)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        if last_batch_handle == "discard":
            self.num_data -= self.num_data % batch_size
        self.cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                -self.batch_size < self.cursor < 0:
            self.cursor = -self.batch_size + (self.cursor % self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for k, v in arrays:
            start = self.cursor
            end = self.cursor + self.batch_size
            if end <= self.num_data:
                sel = self.idx[start:end]
            else:  # pad by wrapping
                pad = end - self.num_data
                sel = _np.concatenate([self.idx[start:], self.idx[:pad]])
            out.append(nd.array(v.asnumpy()[sel], dtype=str(v.dtype)))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        end = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[self.cursor:end]


class CSVIter(DataIter):
    """CSV file iterator (reference src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",",
                           dtype=_np.dtype(dtype)).reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = _np.zeros((data.shape[0],) + tuple(label_shape), _np.float32)
        self._inner = NDArrayIter(data, label, batch_size=batch_size,
                                  last_batch_handle="pad" if round_batch else
                                  "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference io.py)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label


class PrefetchingIter(DataIter):
    """Background-thread prefetch (reference io.py PrefetchingIter over
    dmlc::ThreadedIter — here a plain producer thread + queue).

    With ``device=True`` (or an explicit jax device, or a ``mesh``) a
    SECOND pipeline stage consumes the host queue through
    :class:`.prefetch.DevicePrefetcher`: the host thread keeps overlapping
    decode/augment, while the device stage issues the async host->HBM copy
    of batch N+1 under batch N's compute — the full analog of the
    reference's iter_prefetcher.h double buffer, extended past host RAM.
    """

    def __init__(self, iters, rename_data=None, rename_label=None, depth=2,
                 device=False, mesh=None, axis="dp"):
        if not isinstance(iters, list):
            iters = [iters]
        assert len(iters) == 1, "single backing iter supported"
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        import queue
        self._depth = depth
        self._device = device
        self._mesh = mesh
        self._axis = axis
        self._queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = None
        self._dev = None
        self._start()

    def _start(self):
        def worker():
            while not self._stop.is_set():
                try:
                    batch = self.iter.next()
                except StopIteration:
                    self._queue.put(None)
                    return
                self._queue.put(batch)

        self._thread = threading.Thread(target=worker,
                                        name="mxtpu-io-prefetch",
                                        daemon=True)
        self._thread.start()
        if self._device or self._mesh is not None:
            from .prefetch import DevicePrefetcher
            dev = self._device if self._device not in (True, False) else None
            self._dev = DevicePrefetcher(self._host_drain(),
                                         size=self._depth, mesh=self._mesh,
                                         axis=self._axis, device=dev)

    def _host_drain(self):
        """Generator feeding the device stage from the host queue. Polls
        with a timeout so reset()/close() (which set _stop) can't leave
        the device-stage worker blocked forever on an idle queue."""
        import queue
        while not self._stop.is_set():
            try:
                batch = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            if batch is None:
                return
            yield batch

    def reset(self):
        self._stop.set()
        if self._dev is not None:
            self._dev.close()
            self._dev = None
        if self._thread is not None:
            while not self._queue.empty():
                self._queue.get_nowait()
            self._thread.join(timeout=5)
            # a worker blocked in put() is unblocked by the drain above and
            # may land one stale batch before it sees _stop; sweep it out
            # so the next epoch starts clean
            while not self._queue.empty():
                self._queue.get_nowait()
        self.iter.reset()
        self._stop.clear()
        self._start()

    def next(self):
        if self._dev is not None:
            return next(self._dev)      # StopIteration terminates the epoch
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label


class MNISTIter(DataIter):
    """IDX-format MNIST reader (reference src/io/iter_mnist.cc): parses the
    ubyte image/label files directly, normalizes to [0,1] when flat=False
    per the reference's input_scale, supports shuffle/partitioning."""

    def __init__(self, image, label, batch_size=128, shuffle=False,
                 flat=False, silent=True, seed=0, part_index=0, num_parts=1,
                 **kwargs):
        super().__init__(batch_size)
        import gzip
        import struct

        def _open(path):
            return gzip.open(path, "rb") if path.endswith(".gz") \
                else open(path, "rb")

        with _open(image) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise MXNetError(f"{image}: bad MNIST image magic {magic}")
            imgs = _np.frombuffer(f.read(n * rows * cols), _np.uint8)
            imgs = imgs.reshape(n, rows, cols)
        with _open(label) as f:
            magic, n2 = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise MXNetError(f"{label}: bad MNIST label magic {magic}")
            labs = _np.frombuffer(f.read(n2), _np.uint8).astype(_np.float32)
        if num_parts > 1:
            step = (n + num_parts - 1) // num_parts
            sl = slice(part_index * step, min(n, (part_index + 1) * step))
            imgs, labs = imgs[sl], labs[sl]
        if shuffle:
            perm = _np.random.RandomState(seed).permutation(len(imgs))
            imgs, labs = imgs[perm], labs[perm]
        data = imgs.astype(_np.float32) / 255.0
        data = data.reshape(len(imgs), -1) if flat \
            else data[:, None, :, :]
        self._inner = NDArrayIter(data, labs, batch_size=batch_size,
                                  last_batch_handle="pad")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """libsvm text reader (reference src/io/iter_libsvm.cc). Rows become
    CSR storage; batches are returned as CSRNDArray data + dense labels
    (the reference's sparse batch path, iter_sparse_batchloader.h)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        ncol = int(data_shape[0] if hasattr(data_shape, "__len__")
                   else data_shape)
        indptr, indices, values, labels = [0], [], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    indices.append(int(i))
                    values.append(float(v))
                indptr.append(len(indices))
        self._indptr = _np.asarray(indptr, _np.int32)
        self._indices = _np.asarray(indices, _np.int32)
        self._values = _np.asarray(values, _np.float32)
        self._labels = _np.asarray(labels, _np.float32)
        if label_libsvm is not None:
            ext = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.split():
                        ext.append(float(line.split()[0]))
            self._labels = _np.asarray(ext, _np.float32)
        self._ncol = ncol
        self._n = len(self._labels)
        self._round = round_batch
        self.cursor = 0

    def reset(self):
        self.cursor = 0

    def next(self):
        from ..ndarray.sparse import csr_matrix
        if self.cursor >= self._n:
            raise StopIteration
        lo = self.cursor
        hi = min(lo + self.batch_size, self._n)
        self.cursor += self.batch_size
        nrow = hi - lo
        if nrow < self.batch_size and not self._round:
            # keep batches a fixed shape (provide_data's contract): without
            # round_batch the trailing partial batch is discarded
            raise StopIteration
        # rows are stored contiguously, so a batch is one slice of the CSR
        # buffers plus a rebased indptr — no per-element python loop
        s, e = int(self._indptr[lo]), int(self._indptr[hi])
        ptr = (self._indptr[lo:hi + 1] - self._indptr[lo]).astype(_np.int32)
        pad = self.batch_size - nrow
        if pad:
            ptr = _np.concatenate(
                [ptr, _np.full(pad, ptr[-1], _np.int32)])
        data = csr_matrix((self._values[s:e], self._indices[s:e], ptr),
                          shape=(self.batch_size, self._ncol))
        lab = self._labels[lo:hi]
        if pad:
            lab = _np.concatenate([lab, _np.zeros(pad, _np.float32)])
        from ..ndarray.ndarray import NDArray
        return DataBatch(data=[data], label=[NDArray(lab)], pad=pad)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._ncol))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]


def MXDataIter(name, **kwargs):
    """Factory matching the reference's C++-registered iterators
    (reference io.py:790 MXDataIter; MXListDataIters)."""
    from ..image.image_iter import ImageRecordIter as _IRI
    table = {"ImageRecordIter": _IRI, "CSVIter": CSVIter,
             "NDArrayIter": NDArrayIter, "MNISTIter": MNISTIter,
             "LibSVMIter": LibSVMIter}
    if name not in table:
        raise MXNetError(f"unknown data iter {name}")
    return table[name](**kwargs)


def ImageRecordIter(**kwargs):
    """Reference src/io/iter_image_recordio_2.cc via the Python surface."""
    from ..image.image_iter import ImageRecordIter as _IRI
    return _IRI(**kwargs)
