"""RecordIO: sequential & indexed record files + image record packing.

Reference: python/mxnet/recordio.py (509 LoC: MXRecordIO/MXIndexedRecordIO,
IRHeader pack/unpack, pack_img) over dmlc-core's C++ RecordIO streams.

Format (kept binary-compatible with the reference so .rec datasets interop):
  each record = [uint32 magic 0xced7230a][uint32 lrecord][data][pad to 4B]
  where lrecord = (cflag<<29) | length; cflag encodes multi-part records.
The C++ fast path (native/recordio.cpp via ctypes) is used when built — the
reference's dmlc::RecordIOReader equivalent — with a pure-python fallback.
"""
from __future__ import annotations

import ctypes
import os
import struct

import numpy as _np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A


def _native():
    """The C++ codec (native/recordio.cc), None if g++/load unavailable."""
    from .util import getenv_bool
    if getenv_bool("MXTPU_NO_NATIVE"):
        return None
    try:
        from . import native
        return native if native.load() is not None else None
    except Exception:
        return None


class MXRecordIO:
    """Sequential record file reader/writer (reference recordio.py:34).

    Uses the native C++ codec when available (multipart framing + buffered
    IO in C), transparently falling back to the pure-python path."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.is_open = False
        self._nat = None
        self.open()

    def open(self):
        nat = _native()
        if self.flag == "w":
            self.writable = True
            if nat is not None:
                self._nat = nat.NativeRecordWriter(self.uri)
                self.record = None
            else:
                self.record = open(self.uri, "wb")
        elif self.flag == "r":
            self.writable = False
            if nat is not None:
                self._nat = nat.NativeRecordReader(self.uri)
                self.record = None
            else:
                self.record = open(self.uri, "rb")
        else:
            raise MXNetError(f"invalid flag {self.flag}")
        self.pid = os.getpid()
        self.is_open = True

    def close(self):
        if self.is_open:
            if self._nat is not None:
                self._nat.close()
                self._nat = None
            else:
                self.record.close()
            self.is_open = False
            self.pid = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        d["_nat"] = None          # ctypes handles don't pickle
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()
        if self.flag == "r":
            pass

    def _check_pid(self):
        # reference resets readers after fork (recordio.py reset on pid change)
        if self.pid != os.getpid():
            self.reset()

    def reset(self):
        self.close()
        self.open()

    # cflag values in the lrecord high bits (dmlc-core recordio multipart
    # encoding): 0=complete, 1=begin, 2=middle, 3=end
    _LEN_MASK = (1 << 29) - 1
    _CHUNK = (1 << 29) - 4     # max payload per physical record

    def _write_one(self, cflag, data):
        lrec = (cflag << 29) | len(data)
        self.record.write(struct.pack("<II", _MAGIC, lrec))
        self.record.write(data)
        pad = (4 - (len(data) % 4)) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def write(self, buf):
        assert self.writable
        data = bytes(buf)
        if self._nat is not None:
            self._nat.write(data)
            return
        if len(data) <= self._LEN_MASK:
            self._write_one(0, data)
            return
        # oversized: split into begin/middle.../end physical records
        chunks = [data[i:i + self._CHUNK]
                  for i in range(0, len(data), self._CHUNK)]
        for i, c in enumerate(chunks):
            cflag = 1 if i == 0 else (3 if i == len(chunks) - 1 else 2)
            self._write_one(cflag, c)

    def _read_one(self):
        header = self.record.read(8)
        if len(header) < 8:
            return None, None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic; corrupt file?")
        cflag = lrec >> 29
        length = lrec & self._LEN_MASK
        data = self.record.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.record.read(pad)
        return cflag, data

    def read(self):
        assert not self.writable
        self._check_pid()
        if self._nat is not None:
            return self._nat.read()
        cflag, data = self._read_one()
        if data is None:
            return None
        if cflag == 0:
            return data
        if cflag != 1:
            raise MXNetError(f"multipart record starts with cflag {cflag}; "
                             "corrupt or mid-stream seek")
        parts = [data]
        while True:
            cflag, data = self._read_one()
            if data is None:
                raise MXNetError("truncated multipart record")
            parts.append(data)
            if cflag == 3:
                return b"".join(parts)
            if cflag != 2:
                raise MXNetError(f"unexpected cflag {cflag} inside "
                                 "multipart record")

    def tell(self):
        if self._nat is not None:
            return self._nat.tell()
        return self.record.tell()

    def seek(self, pos):
        assert not self.writable
        self._check_pid()
        if self._nat is not None:
            self._nat.seek(pos)
        else:
            self.record.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Indexed record file (reference recordio.py:133): .idx maps key->offset."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.exists(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


# header for image records (reference recordio.py IRHeader)
import collections

IRHeader = collections.namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + payload into a record payload (reference recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, header.flag, float(header.label),
                          header.id, header.id2)
    else:
        label = _np.asarray(header.label, dtype=_np.float32)
        hdr = struct.pack(_IR_FORMAT, len(label), 0.0, header.id, header.id2)
        hdr += label.tobytes()
    return hdr + s


def unpack(s):
    """Reference recordio.py unpack."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        label = _np.frombuffer(payload[:header.flag * 4], dtype=_np.float32)
        header = header._replace(label=label)
        payload = payload[header.flag * 4:]
    return header, payload


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Reference recordio.py pack_img (OpenCV imencode there; PIL here)."""
    from .image.image import imencode
    return pack(header, imencode(img, quality=quality, fmt=img_fmt))


def unpack_img(s, iscolor=1):
    header, payload = unpack(s)
    from .image.image import imdecode_np
    return header, imdecode_np(payload, iscolor)
