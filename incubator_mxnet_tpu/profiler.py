"""Profiler: op-level events + chrome-trace output + aggregate stats.

Reference: src/profiler/profiler.h:251 (typed stats in per-thread buffers,
chrome://tracing JSON at profiler.h:79,432, DumpProfile:299, aggregate
table aggregate_stats.cc) and python/mxnet/profiler.py (set_config /
set_state / start / stop / dump / dumps + scoped markers).

TPU-native redesign: engine-op instrumentation becomes a dispatch hook on
the op registry (the only choke point every eager/compiled call crosses),
and kernel-level detail comes from jax.profiler (XPlane) when a tensorboard
directory is configured. Dispatch is async under XLA — `profile_sync=True`
(the default while profiling) blocks on each op's output so durations are
real compute times, mirroring the reference's GPU stream-sync profiling
mode (profiler.h kSimple vs kAccurate).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict

from .base import MXNetError

__all__ = ["set_config", "set_state", "start", "stop", "dump", "dumps",
           "pause", "resume", "is_running", "Scope", "Task", "Event",
           "Counter", "Marker"]

_lock = threading.Lock()
_state = {
    "running": False,
    "paused": False,
    "filename": "profile.json",
    "aggregate_stats": False,
    "sync": True,
    "tb_dir": None,
    "tb_active": False,
}
_events = []  # (name, category, start_us, dur_us, tid)
_counters = []  # (name, ts_us, value)


def set_config(filename="profile.json", profile_all=False,
               profile_symbolic=True, profile_imperative=True,
               profile_memory=False, profile_api=False,
               aggregate_stats=False, continuous_dump=False,
               dump_period=1.0, profile_sync=True, tensorboard_dir=None,
               **kwargs):
    """Reference profiler.py set_config / MXSetProcessProfilerConfig."""
    _state["filename"] = filename
    _state["aggregate_stats"] = aggregate_stats
    _state["sync"] = profile_sync
    _state["tb_dir"] = tensorboard_dir


def set_state(state="stop", profile_process="worker"):
    """'run' or 'stop' (reference profiler.py set_state)."""
    if state == "run":
        start()
    elif state == "stop":
        stop()
    else:
        raise MXNetError(f"invalid profiler state {state!r}")


def start(profile_process="worker"):
    from .ops import registry
    _state["running"] = True
    _state["paused"] = False
    registry.PROFILER_HOOK = _op_hook
    if _state["tb_dir"]:
        import jax
        os.makedirs(_state["tb_dir"], exist_ok=True)
        jax.profiler.start_trace(_state["tb_dir"])
        _state["tb_active"] = True


def stop(profile_process="worker"):
    from .ops import registry
    _state["running"] = False
    registry.PROFILER_HOOK = None
    if _state.get("tb_active"):
        import jax
        jax.profiler.stop_trace()
        _state["tb_active"] = False


def is_running():
    """True while the profiler is collecting (started and not paused).
    Periodic publishers (Trainer step counters, serving stats) gate their
    Counter.set_value calls on this so an idle profiler doesn't accumulate
    an unbounded counter series."""
    return _state["running"] and not _state["paused"]


def pause(profile_process="worker"):
    _state["paused"] = True


def resume(profile_process="worker"):
    _state["paused"] = False


def _op_hook(name, fn, args):
    """Installed into registry.PROFILER_HOOK: time one op dispatch."""
    if not _state["running"] or _state["paused"]:
        return fn(*args)
    t0 = time.perf_counter()
    out = fn(*args)
    if _state["sync"]:
        _block(out)
    dur = (time.perf_counter() - t0) * 1e6
    with _lock:
        _events.append((name, "operator", t0 * 1e6, dur,
                        threading.get_ident()))
    return out


def _block(out):
    if isinstance(out, (tuple, list)):
        for o in out:
            _block(o)
    elif hasattr(out, "block_until_ready"):
        out.block_until_ready()


def _record(name, category, t0_us, dur_us):
    with _lock:
        _events.append((name, category, t0_us, dur_us,
                        threading.get_ident()))


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (reference MXDumpProfile;
    profiler.h:79 'chrome tracing json')."""
    with _lock:
        events = list(_events)
        counters = list(_counters)
        if finished:
            _events.clear()
            _counters.clear()
    trace = []
    for name, cat, ts, dur, tid in events:
        trace.append({"name": name, "cat": cat, "ph": "X", "ts": ts,
                      "dur": dur, "pid": 0, "tid": tid})
    for name, ts, value in counters:
        trace.append({"name": name, "ph": "C", "ts": ts, "pid": 0,
                      "args": {"value": value}})
    with open(_state["filename"], "w") as f:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)
    return _state["filename"]


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate-stats table string (reference
    MXAggregateProfileStatsPrint / aggregate_stats.cc). Counter series
    (profiler.Counter — op counts, serving queue depth / shed totals from
    serve/stats.py) are aggregated into their own section: last value +
    sample count per counter name."""
    with _lock:
        events = list(_events)
        counters = list(_counters)
        if reset:
            _events.clear()
            _counters.clear()
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    for name, cat, ts, dur, tid in events:
        a = agg[name]
        a[0] += 1
        a[1] += dur
        a[2] = min(a[2], dur)
        a[3] = max(a[3], dur)
    cagg = {}
    for name, ts, value in counters:
        cnt = cagg[name][0] + 1 if name in cagg else 1
        cagg[name] = (cnt, value)
    if format == "json":
        return json.dumps({
            "stats": {k: {"count": v[0], "total_us": v[1],
                          "min_us": v[2], "max_us": v[3]}
                      for k, v in agg.items()},
            "counters": {k: {"samples": c, "value": v}
                         for k, (c, v) in cagg.items()}})
    lines = [f"{'Name':<40}{'Count':>8}{'Total(us)':>14}"
             f"{'Min(us)':>12}{'Max(us)':>12}{'Avg(us)':>12}",
             "-" * 98]
    key = {"total": lambda kv: kv[1][1], "count": lambda kv: kv[1][0],
           "min": lambda kv: kv[1][2], "max": lambda kv: kv[1][3],
           "avg": lambda kv: kv[1][1] / max(kv[1][0], 1)}[sort_by]
    for name, (cnt, tot, mn, mx) in sorted(agg.items(), key=key,
                                           reverse=not ascending):
        lines.append(f"{name:<40}{cnt:>8}{tot:>14.1f}{mn:>12.1f}"
                     f"{mx:>12.1f}{tot / max(cnt, 1):>12.1f}")
    if cagg:
        lines += ["", f"{'Counter':<48}{'Samples':>10}{'Value':>16}",
                  "-" * 74]
        for name, (cnt, val) in sorted(cagg.items()):
            sval = f"{val:.3f}" if isinstance(val, float) else f"{val}"
            lines.append(f"{name:<48}{cnt:>10}{sval:>16}")
    return "\n".join(lines)


class _Timed:
    """Scoped marker base (reference profiler.py Task/Event/Frame)."""

    def __init__(self, name, category):
        self._name = name
        self._category = category
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is None:
            return
        dur = (time.perf_counter() - self._t0) * 1e6
        _record(self._name, self._category, self._t0 * 1e6, dur)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Scope(_Timed):
    def __init__(self, name="<unk>:"):
        super().__init__(name, "scope")


class Task(_Timed):
    def __init__(self, domain=None, name="task"):
        super().__init__(name, "task")


class Event(_Timed):
    def __init__(self, name="event"):
        super().__init__(name, "event")


class Marker:
    """Instant marker (reference profiler.py Marker.mark)."""

    def __init__(self, domain=None, name="marker"):
        self._name = name

    def mark(self, scope="process"):
        _record(self._name, "marker", time.perf_counter() * 1e6, 0)


class Counter:
    """Numeric counter series (reference profiler.py Counter)."""

    def __init__(self, domain=None, name="counter", value=None):
        self._name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        with _lock:
            _counters.append((self._name, time.perf_counter() * 1e6, value))

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self
