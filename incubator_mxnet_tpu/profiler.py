"""Profiler: op-level events + chrome-trace output + aggregate stats +
runtime telemetry (memory profiler, jit-recompile tracker, Prometheus
scrape surface).

Reference: src/profiler/profiler.h:251 (typed stats in per-thread buffers,
chrome://tracing JSON at profiler.h:79,432, DumpProfile:299, aggregate
table aggregate_stats.cc, GPU memory profiler behind profile_memory) and
python/mxnet/profiler.py (set_config / set_state / start / stop / dump /
dumps + scoped markers + Domain/Task/Event/Counter/Marker).

TPU-native redesign: engine-op instrumentation becomes a dispatch hook on
the op registry (the only choke point every eager/compiled call crosses),
and kernel-level detail comes from jax.profiler (XPlane) when a tensorboard
directory is configured. Dispatch is async under XLA — `profile_sync=True`
(the default while profiling) blocks on each op's output so durations are
real compute times, mirroring the reference's GPU stream-sync profiling
mode (profiler.h kSimple vs kAccurate).

Three telemetry layers beyond the reference:

- **Memory profiler** (`profile_memory=True`): NDArray construction and the
  fused-step donation path report device buffers here; live/peak bytes are
  accounted per device in pure python (finalizers decrement on free) and
  emitted as `ph:"C"` counter tracks in the chrome trace plus a Memory
  section in dumps(). The reference's analog is the GpuDeviceStorageProfiler
  (storage_profiler.h) behind the same config flag.
- **Jit/compile tracker**: every cached-jit choke point the framework owns
  (op registry, fused optimizer dispatch, kvstore flat-pack, serving
  executables) wraps its compiled callable in `track_jit(key, fn)`, which
  detects XLA recompilation per call (via the jit cache size) and records
  it through `compile_event(key, cache_hit, compile_ms)`. A cache key
  recompiling more than MXNET_COMPILE_WARN_THRESHOLD times logs a warning —
  the classic leaked-python-scalar / unbucketed-shape bug.
- **Scrape surface**: `render_prometheus()` serializes the counter/gauge
  registry in Prometheus text exposition format (served at GET /metrics by
  serve/server.py), and `continuous_dump`/`dump_period` run a daemon thread
  writing rolling chrome traces for long training runs.
"""
from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import weakref
from collections import defaultdict

from .base import MXNetError
from . import mxsan as _mxsan

__all__ = ["set_config", "set_state", "start", "stop", "dump", "dumps",
           "pause", "resume", "is_running", "Scope", "Task", "Event",
           "Counter", "Marker", "Domain", "compile_event", "compile_stats",
           "compile_totals", "track_jit", "memory_event", "memory_stats",
           "memory_enabled", "render_prometheus",
           "span", "observe_phase", "request_phase", "attribution_enabled",
           "attribution_enable",
           "attribution_reset", "phase_stats", "phase_step_end",
           "last_step_phases", "span_records", "next_span_id", "trace_id",
           "clock_sync_event", "cost_event", "cost_stats",
           "cost_from_executable", "device_peak_flops", "mfu_stats"]

_lock = _mxsan.lock("profiler.py", "_lock")
_state = {
    "running": False,
    "paused": False,
    "filename": "profile.json",
    "aggregate_stats": False,
    "sync": True,
    "tb_dir": None,
    "tb_active": False,
    "profile_memory": False,
    "continuous": False,
    "dump_period": 1.0,
}
# event dicts: {"name","cat","ts","dur","tid","ph"} (+optional "s","args")
_events = []
_counters = []       # (name, ts_us, value) sample series
_counter_last = {}   # name -> latest value (the Prometheus gauge registry)
# rolling (continuous_dump) trims fold into these so dumps() still
# aggregates the whole run while each trace segment stays bounded
_agg_events = {}     # name -> [count, total_us, min_us, max_us]
_agg_counts = {}     # counter name -> folded sample count
_dump_seq = 0        # rolling trace segment number (never reused)


def set_config(filename="profile.json", profile_all=False,
               profile_symbolic=True, profile_imperative=True,
               profile_memory=False, profile_api=False,
               aggregate_stats=False, continuous_dump=False,
               dump_period=1.0, profile_sync=True, tensorboard_dir=None,
               **kwargs):
    """Reference profiler.py set_config / MXSetProcessProfilerConfig."""
    _state["filename"] = filename
    _state["aggregate_stats"] = aggregate_stats
    _state["sync"] = profile_sync
    _state["tb_dir"] = tensorboard_dir
    _state["profile_memory"] = bool(profile_memory)
    _state["continuous"] = bool(continuous_dump)
    _state["dump_period"] = max(float(dump_period), 0.05)


def set_state(state="stop", profile_process="worker"):
    """'run' or 'stop' (reference profiler.py set_state)."""
    if state == "run":
        start()
    elif state == "stop":
        stop()
    else:
        raise MXNetError(f"invalid profiler state {state!r}")


def start(profile_process="worker"):
    from .ops import registry
    _state["running"] = True
    _state["paused"] = False
    # a start() opens a fresh profiling window: compile telemetry gathered
    # before it (the registry records always-on) belongs to the previous
    # window and would pollute this session's dumps()/compile table
    with _clock:
        _compile.clear()
        _compile_warned.clear()
    registry.PROFILER_HOOK = _op_hook
    if _state["profile_memory"]:
        _mem["enabled"] = True
        from .ndarray import ndarray as _ndmod
        _ndmod.MEMORY_HOOK = _note_alloc
    if _state["continuous"]:
        _start_dump_thread()
    if _state["tb_dir"]:
        import jax
        os.makedirs(_state["tb_dir"], exist_ok=True)
        jax.profiler.start_trace(_state["tb_dir"])
        _state["tb_active"] = True


def stop(profile_process="worker"):
    from .ops import registry
    _state["running"] = False
    registry.PROFILER_HOOK = None
    # uninstall the allocation hook (accounting stays readable in dumps())
    _mem["enabled"] = False
    from .ndarray import ndarray as _ndmod
    _ndmod.MEMORY_HOOK = None
    _stop_dump_thread()
    if _state.get("tb_active"):
        import jax
        jax.profiler.stop_trace()
        _state["tb_active"] = False


def is_running():
    """True while the profiler is collecting (started and not paused).
    Periodic publishers (Trainer step counters, serving stats) gate their
    Counter.set_value calls on this so an idle profiler doesn't accumulate
    an unbounded counter series."""
    return _state["running"] and not _state["paused"]


def pause(profile_process="worker"):
    _state["paused"] = True


def resume(profile_process="worker"):
    _state["paused"] = False


# ---------------------------------------------------------------------------
# continuous dump (reference profiler.h continuous_dump_: rolling traces so
# a long run that never reaches a clean exit still leaves profile data)
# ---------------------------------------------------------------------------

_dump_thread = None
_dump_stop = threading.Event()


def _start_dump_thread():
    global _dump_thread
    if _dump_thread is not None and _dump_thread.is_alive():
        return
    _dump_stop.clear()

    def _loop():
        while not _dump_stop.wait(_state["dump_period"]):
            if _state["running"]:
                try:
                    dump(finished=False)
                except Exception:       # noqa: BLE001 — never kill the run
                    logging.exception("profiler continuous dump failed")

    _dump_thread = threading.Thread(target=_loop, name="mxtpu-profiler-dump",
                                    daemon=True)
    _dump_thread.start()


def _stop_dump_thread():
    global _dump_thread
    _dump_stop.set()
    t, _dump_thread = _dump_thread, None
    if t is not None and t.is_alive():
        t.join(timeout=5)


# ---------------------------------------------------------------------------
# event recording
# ---------------------------------------------------------------------------

def _op_hook(name, fn, args):
    """Installed into registry.PROFILER_HOOK: time one op dispatch."""
    if not _state["running"] or _state["paused"]:
        return fn(*args)
    t0 = time.perf_counter()
    out = fn(*args)
    if _state["sync"]:
        _block(out)
    dur = (time.perf_counter() - t0) * 1e6
    with _lock:
        _events.append({"name": name, "cat": "operator", "ts": t0 * 1e6,
                        "dur": dur, "tid": threading.get_ident(), "ph": "X"})
    return out


def _block(out):
    if isinstance(out, (tuple, list)):
        for o in out:
            _block(o)
    elif hasattr(out, "block_until_ready"):
        out.block_until_ready()


def _record(name, category, t0_us, dur_us, ph="X", scope=None, args=None):
    ev = {"name": name, "cat": category, "ts": t0_us, "dur": dur_us,
          "tid": threading.get_ident(), "ph": ph}
    if scope is not None:
        ev["s"] = scope
    if args is not None:
        ev["args"] = args
    with _lock:
        _events.append(ev)


def _counter_sample(name, value):
    """Append one sample to the counter series and refresh the last-value
    registry. Callers that need atomic read-modify-write (Counter) hold
    `_lock` already and use `_counter_sample_locked`."""
    with _lock:
        _counter_sample_locked(name, value)


def _counter_sample_locked(name, value):
    _counters.append((name, time.perf_counter() * 1e6, value))
    _counter_last[name] = value


# ---------------------------------------------------------------------------
# jit/compile tracker
# ---------------------------------------------------------------------------

_clock = _mxsan.lock("profiler.py", "_clock")
# key -> [hits, misses, compile_ms_total, last_ms, disk_hits]; disk_hits
# counts the subset of hits served by deserializing a persistent-cache
# entry (compile_cache disk tier) rather than reusing an in-process one
_compile = {}
_compile_warned = set()


def _warn_threshold():
    from .util import getenv_int
    return getenv_int("MXNET_COMPILE_WARN_THRESHOLD")


def compile_event(key, cache_hit, compile_ms=0.0, disk=False):
    """Record one lookup against a compiled-executable cache.

    key:       stable cache identity ("op:dot", "fused:adam_update[n=4]",
               "kvstore:flat_pack[13]", "serve:exec[8x6]", ...)
    cache_hit: True when an already-compiled executable served the call
    compile_ms: trace+compile wall time charged to a miss
    disk:      the hit deserialized a persistent compile_cache entry (a
               fresh process avoiding an XLA retrace) rather than reusing
               an executable already loaded in this process

    Always-on (independent of start/stop): recompile pathologies are
    exactly the thing you need visibility into *before* deciding to
    profile. pause() still suppresses it — pause is the explicit "don't
    record this region" request. A key whose miss count passes
    MXNET_COMPILE_WARN_THRESHOLD logs one warning — the classic
    silent-recompile-per-step bug (leaked python scalar in a param,
    shape bucket miss, donation failure).
    """
    if _state["paused"]:
        return
    warn = None
    with _clock:
        rec = _compile.get(key)
        if rec is None:
            rec = _compile[key] = [0, 0, 0.0, 0.0, 0]
        if cache_hit:
            rec[0] += 1
            if disk:
                rec[4] += 1
        else:
            rec[1] += 1
            rec[2] += float(compile_ms)
            rec[3] = float(compile_ms)
            if rec[1] > _warn_threshold() and key not in _compile_warned:
                _compile_warned.add(key)
                warn = rec[1]
    if warn is not None:
        logging.warning(
            "profiler: cache key %r has compiled %d times "
            "(MXNET_COMPILE_WARN_THRESHOLD=%d) — a python scalar leaking "
            "into a traced program or an unbucketed shape is recompiling "
            "every step", key, warn, _warn_threshold())


def compile_stats():
    """Snapshot {key: {hits, misses, compile_ms, last_compile_ms,
    disk_hits}} (disk_hits <= hits: persistent-cache deserializes)."""
    with _clock:
        return {k: {"hits": v[0], "misses": v[1],
                    "compile_ms": v[2], "last_compile_ms": v[3],
                    "disk_hits": v[4]}
                for k, v in _compile.items()}


def compile_totals():
    """(total_hits, total_misses) over every tracked cache. The Trainer
    diffs the miss total around each step into `recompiles_per_step`."""
    with _clock:
        h = m = 0
        for v in _compile.values():
            h += v[0]
            m += v[1]
        return h, m


def track_jit(key, fn):
    """Wrap a jax.jit-compiled callable so every call records a
    compile_event: a call that grows the executable's internal cache (new
    shape/dtype signature -> XLA retrace+compile) is a miss charged with
    the call's wall time; a steady-state call is a hit.

    Falls back to first-call-is-the-miss accounting when the jit internals
    don't expose a cache size (older jax, non-jit callables).
    """
    probe = getattr(fn, "_cache_size", None)
    # first-call detection must be atomic: concurrent first calls would
    # otherwise both read called=False and both record a miss (the CC01
    # unlocked read-modify-write pattern mxlint polices)
    state = {"called": False, "captured": False}
    state_lock = _mxsan.lock("profiler.py", "state_lock")

    def _maybe_capture(args, kwargs):
        # shardlint graph capture for track_jit sites that did not route
        # through cached_jit: re-trace the jitted callable once (analysis
        # mode only — enabled() is off in production)
        from . import shardlint as _sl
        if not _sl.enabled():
            return
        tracer = getattr(fn, "trace", None)
        if tracer is None:
            return
        try:
            _sl.record_jit(key, traced=tracer(*args, **kwargs))
        except Exception:       # noqa: BLE001 — capture must never break a call
            pass

    def wrapped(*args, **kwargs):
        if not state["captured"]:
            with state_lock:
                first_capture = not state["captured"]
                state["captured"] = True
            if first_capture:
                _maybe_capture(args, kwargs)
        before = None
        if probe is not None:
            try:
                before = probe()
            except Exception:       # noqa: BLE001
                before = None
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt_ms = (time.perf_counter() - t0) * 1e3
        after = None
        if probe is not None:
            try:
                after = probe()
            except Exception:       # noqa: BLE001
                after = None
        if before is None or after is None:
            with state_lock:
                first = not state["called"]
                state["called"] = True
            compile_event(key, cache_hit=not first,
                          compile_ms=dt_ms if first else 0.0)
        elif after > before:
            compile_event(key, cache_hit=False, compile_ms=dt_ms)
        else:
            compile_event(key, cache_hit=True)
        return out

    wrapped.__wrapped__ = fn
    wrapped._compile_key = key
    return wrapped


# ---------------------------------------------------------------------------
# step-time attribution (StepTimeline): profiler.span(phase) attributes every
# train step / serve request into named phases — input_wait, h2d, compute,
# collective, optimizer, ckpt_snapshot, queue_wait. Gated on
# MXNET_STEP_ATTRIBUTION with the shardlint cached-boolean pattern: off (the
# default) the hot paths take the gate branch and nothing else — span()
# is never even called, and _span_records stays 0 (counter-asserted).
# ---------------------------------------------------------------------------

_attr_enabled = None        # cached MXNET_STEP_ATTRIBUTION read
# log-spaced ms histogram bounds shared by every phase (floor 10us, x1.6):
# rendered as mxnet_step_phase_ms Prometheus histograms
_PHASE_BOUNDS = tuple(0.01 * (1.6 ** i) for i in range(30))
# phase -> [count, total_ms, max_ms, last_ms, bucket_counts[len+1]]
_phases = {}
_span_records = 0           # spans actually booked (zero-overhead assert)
_span_seq = 0               # process-wide span-id counter (wire-propagated)
_span_tls = threading.local()   # per-thread active-span stack (nesting)
_trace_id = None            # lazy per-process trace identity
_step_phases_cur = {}       # phase -> ms accumulated in the step in flight
_step_phases_last = {}      # previous step's phase vector (heartbeats)
_step_seq = 0               # steps closed by phase_step_end()


def attribution_enabled():
    """True when step-time attribution is on. The env var is read once
    and cached — the gate sits on the per-batch hot path."""
    global _attr_enabled
    if _attr_enabled is None:
        from .util import getenv_bool
        _attr_enabled = getenv_bool("MXNET_STEP_ATTRIBUTION")
    return _attr_enabled


def attribution_enable(on=True):
    """Force attribution on/off for this process (tests, bench); returns
    the previous effective state."""
    global _attr_enabled
    prev = attribution_enabled()
    _attr_enabled = bool(on)
    return prev


def attribution_reset():
    """Forget the cached MXNET_STEP_ATTRIBUTION read and drop all phase
    state — the next attribution_enabled() consults the environment."""
    global _attr_enabled
    _attr_enabled = None
    with _lock:
        _reset_phases_locked()


def _reset_phases_locked():
    global _span_records, _step_phases_cur, _step_phases_last, _step_seq
    _phases.clear()
    _span_records = 0
    _step_phases_cur = {}
    _step_phases_last = {}
    _step_seq = 0


def span_records():
    """Spans booked since the last reset. The zero-overhead contract:
    with MXNET_STEP_ATTRIBUTION unset this stays exactly 0 through any
    amount of run_epoch / batcher traffic."""
    with _lock:
        return _span_records


def next_span_id():
    """Process-unique monotonically increasing span id (propagated on the
    kvstore wire so worker push/pull spans link to server handler spans).
    Thread-safe: the increment happens under the module lock."""
    global _span_seq
    with _lock:
        _span_seq += 1
        return _span_seq


def trace_id():
    """Lazy per-process trace identity carried in span args and wire
    headers, so a merged multi-process timeline can attribute every span
    to its origin process."""
    global _trace_id
    if _trace_id is None:
        _trace_id = f"{os.getpid():x}.{int(time.time() * 1e3) & 0xffffffff:x}"
    return _trace_id


def current_span_id():
    """Id of this thread's innermost active span (None outside any span):
    what the kvstore client stamps on outgoing wire frames."""
    stack = getattr(_span_tls, "stack", None)
    return stack[-1][1] if stack else None


class _NullSpan:
    """Shared no-op returned while attribution is off: no allocation, no
    lock, no counter — the off path must cost one boolean check."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_phase", "_args", "_t0", "span_id", "parent_id")

    def __init__(self, phase, args):
        self._phase = phase
        self._args = args
        self._t0 = None
        self.span_id = None
        self.parent_id = None

    def __enter__(self):
        stack = getattr(_span_tls, "stack", None)
        if stack is None:
            stack = _span_tls.stack = []
        self.parent_id = stack[-1][1] if stack else None
        self.span_id = next_span_id()
        stack.append((self._phase, self.span_id))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        dur_ms = (t1 - self._t0) * 1e3
        stack = getattr(_span_tls, "stack", None)
        if stack and stack[-1][1] == self.span_id:
            stack.pop()
        _book_phase(self._phase, self._t0, dur_ms,
                    self.span_id, self.parent_id, self._args)
        return False


def span(phase, args=None):
    """Context manager attributing the enclosed wall time to `phase`.
    While MXNET_STEP_ATTRIBUTION is off this returns a shared no-op; on,
    it books per-phase aggregates + histogram and (while the profiler is
    running) a nested chrome-trace X span carrying span_id/parent/trace
    linkage args."""
    if not attribution_enabled():
        return _NULL_SPAN
    return _Span(str(phase), args)


def observe_phase(phase, dur_ms, t0=None, args=None):
    """Book an externally MEASURED duration into `phase` — for waits that
    cannot be enclosed in a ``with span(...)`` block, like the serve
    batcher's queue_wait (enqueue happened on another thread). `t0` is a
    time.perf_counter()-base start in seconds (defaults to now − dur)."""
    if not attribution_enabled():
        return
    if t0 is None:
        t0 = time.perf_counter() - dur_ms / 1e3
    _book_phase(str(phase), t0, float(dur_ms), next_span_id(), None, args)


def request_phase(phase, t0, dur_ms, span_id, parent_id, extra):
    """Book one request-scoped span from serve/reqtrace.py regardless of
    the MXNET_STEP_ATTRIBUTION gate — the reqtrace layer runs behind its
    own MXNET_REQTRACE gate and has already decided this record should
    exist. Shares the phase aggregates, span-id sequence, and (while the
    profiler is running) the chrome-trace event buffer, so request spans
    land in the same dump files trace_merge joins."""
    _book_phase(str(phase), t0, float(dur_ms), int(span_id), parent_id,
                extra)


def _phase_bucket(dur_ms):
    for i, b in enumerate(_PHASE_BOUNDS):
        if dur_ms <= b:
            return i
    return len(_PHASE_BOUNDS)


def _book_phase(phase, t0, dur_ms, span_id, parent_id, extra):
    global _span_records
    running = _state["running"] and not _state["paused"]
    ev = None
    if running:
        args = {"span_id": span_id, "trace": trace_id()}
        if parent_id is not None:
            args["parent"] = parent_id
        if extra:
            args.update(extra)
        ev = {"name": f"phase:{phase}", "cat": "step", "ts": t0 * 1e6,
              "dur": dur_ms * 1e3, "tid": threading.get_ident(), "ph": "X",
              "args": args}
    with _lock:
        rec = _phases.get(phase)
        if rec is None:
            rec = _phases[phase] = [0, 0.0, 0.0, 0.0,
                                    [0] * (len(_PHASE_BOUNDS) + 1)]
        rec[0] += 1
        rec[1] += dur_ms
        rec[2] = max(rec[2], dur_ms)
        rec[3] = dur_ms
        rec[4][_phase_bucket(dur_ms)] += 1
        _span_records += 1
        # only top-level spans accumulate into the step vector: a nested
        # sub-span's time is already inside its parent's
        if parent_id is None:
            _step_phases_cur[phase] = _step_phases_cur.get(phase, 0.0) \
                + dur_ms
        if ev is not None:
            _events.append(ev)


def phase_step_end():
    """Close the step in flight: the accumulated top-level phase vector
    becomes last_step_phases() (what heartbeats carry to the server's
    straggler report) and the next step starts clean."""
    if not attribution_enabled():
        return
    global _step_phases_cur, _step_phases_last, _step_seq
    with _lock:
        if _step_phases_cur:
            _step_phases_last = _step_phases_cur
            _step_phases_cur = {}
            _step_seq += 1


def last_step_phases():
    """{phase: ms} vector of the most recently closed step (empty until
    attribution records one)."""
    with _lock:
        return dict(_step_phases_last)


def phase_bounds():
    """Upper bucket bounds (ms) of the attribution histograms — shared
    by the local Prometheus exposition and the fleetobs cross-rank
    aggregation (both sides must agree on the bucket layout)."""
    return _PHASE_BOUNDS


def phase_histograms():
    """{phase: {"count", "sum_ms", "buckets"}} snapshot of the raw
    per-phase histogram counts (cumulative since the last reset; the
    final bucket is the +Inf overflow). What fleetobs ships on the
    heartbeat — the coordinator diffs successive snapshots into
    fleet-wide deltas."""
    with _lock:
        return {p: {"count": v[0], "sum_ms": v[1], "buckets": list(v[4])}
                for p, v in _phases.items()}


def phase_stats():
    """Snapshot of the attribution registry: {"steps", "spans",
    "phases": {phase: {count, total_ms, avg_ms, max_ms, last_ms}}}."""
    with _lock:
        return {
            "steps": _step_seq,
            "spans": _span_records,
            "phases": {p: {"count": v[0], "total_ms": v[1],
                           "avg_ms": v[1] / max(v[0], 1),
                           "max_ms": v[2], "last_ms": v[3]}
                       for p, v in _phases.items()},
        }


def clock_sync_event(peer, offset_us, rtt_us):
    """Record one clock-correlation sample against a remote peer as a
    ph:"M" metadata event. Args anchor this process's perf_counter trace
    timebase to its wall clock at the same instant, plus the estimated
    wall offset to the peer — tools/trace_merge.py picks the smallest-RTT
    sample per process to shift its timeline onto the server clock."""
    if not _state["running"] or _state["paused"]:
        return
    now = time.perf_counter() * 1e6
    _record("clock_sync", "__metadata", now, 0, ph="M",
            args={"peer": str(peer), "offset_us": float(offset_us),
                  "rtt_us": float(rtt_us), "perf_anchor_us": now,
                  "wall_anchor_us": time.time() * 1e6,
                  "trace": trace_id()})


# ---------------------------------------------------------------------------
# compiler cost accounting: flops / bytes-accessed / peak memory per cached
# executable, recorded at the cached_jit choke points from XLA's own
# cost_analysis()/memory_analysis() — the compiler, not an analytic formula,
# is the source of truth for model FLOPs and MFU
# ---------------------------------------------------------------------------

# key -> {"flops", "bytes_accessed", "peak_bytes"} (present keys only);
# guarded by _clock next to the compile table it annotates
_costs = {}

_PEAK_TFLOPS = {
    "TPU v4": 275, "TPU v5 lite": 197, "TPU v5e": 197, "TPU v5": 459,
    "TPU v5p": 459, "TPU v6e": 918, "TPU v6": 918, "TPU v7": 4614,
}


def cost_event(key, flops=None, bytes_accessed=None, peak_bytes=None):
    """Record compiler-reported cost for one executable (last write wins:
    a re-compile of the same key refreshes its cost)."""
    if _state["paused"]:
        return
    rec = {}
    for name, v in (("flops", flops), ("bytes_accessed", bytes_accessed),
                    ("peak_bytes", peak_bytes)):
        try:
            v = float(v)
        except (TypeError, ValueError):
            continue
        if v > 0 and v == v and v != float("inf"):
            rec[name] = v
    if not rec:
        return
    with _clock:
        _costs[key] = rec


def cost_from_executable(key, exe):
    """Best-effort extraction of cost_analysis()/memory_analysis() from a
    compiled executable, recorded via cost_event. Every probe is
    defensive: backends may return None, a list, or raise — cost
    accounting must never break a compile. Returns the extracted dict
    (possibly empty) so callers (bench) can reuse the numbers."""
    flops = bytes_accessed = peak = None
    try:
        ca = exe.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            flops = ca.get("flops")
            bytes_accessed = ca.get("bytes accessed")
    except Exception:       # noqa: BLE001
        pass
    try:
        ma = exe.memory_analysis()
        total = 0.0
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes"):
            v = getattr(ma, attr, None)
            if v:
                total += float(v)
        if total > 0:
            peak = total
    except Exception:       # noqa: BLE001
        pass
    cost_event(key, flops=flops, bytes_accessed=bytes_accessed,
               peak_bytes=peak)
    out = {}
    with _clock:
        rec = _costs.get(key)
        if rec:
            out = dict(rec)
    return out


def cost_stats():
    """Snapshot {key: {flops, bytes_accessed, peak_bytes, intensity}}
    (intensity = flops / bytes accessed: the executable's roofline
    position; only derivable when the compiler reported both)."""
    with _clock:
        snap = {k: dict(v) for k, v in _costs.items()}
    for rec in snap.values():
        f, b = rec.get("flops"), rec.get("bytes_accessed")
        if f and b:
            rec["intensity"] = f / b
    return snap


def device_peak_flops():
    """Best-effort peak FLOP/s of device 0 (bf16 matmul peak for known
    TPU generations). None on CPU/unknown kinds — MFU is then null
    rather than a made-up number."""
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:       # noqa: BLE001
        return None
    for k, v in sorted(_PEAK_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.lower().startswith(k.lower()):
            return v * 1e12
    return None


def mfu_stats():
    """MFU derived from compiler cost accounting instead of analytic FLOP
    formulas: model FLOPs/step come from the most-called trainstep
    executable's cost_analysis() and seconds/step from the attributed
    'compute' phase. Returns None until both ingredients exist; "mfu" is
    null off-TPU (no trustworthy peak), the flops rate is still real."""
    with _clock:
        calls = {k: v[0] + v[1] for k, v in _compile.items()}
        costs = {k: dict(v) for k, v in _costs.items()}
    best = None
    for key, rec in costs.items():
        if not key.startswith("trainstep:") or not rec.get("flops"):
            continue
        c = calls.get(key, 0)
        if best is None or c > best[1]:
            best = (key, c, rec)
    if best is None:
        return None
    key, _, rec = best
    with _lock:
        comp = _phases.get("compute")
        compute_ms = comp[1] / max(comp[0], 1) if comp else None
        bub = _phases.get("pp_bubble")
        bubble_ms = bub[1] / max(bub[0], 1) if bub else None
    with _lock:
        # last sample wins: the activation-offload counters (booked by the
        # composed step / HostOffloader) ride along so an offload run's
        # D2H traffic shows up next to its MFU
        offl = {}
        for name, _ts, val in _counters:
            if name in ("d2h_bytes", "offload_wait_ms_per_step"):
                offl[name] = val
    out = {"key": key, "flops_per_step": rec["flops"],
           "bytes_per_step": rec.get("bytes_accessed"),
           "compute_ms_per_step": compute_ms,
           "pp_bubble_ms_per_step": bubble_ms,
           "pp_bubble_fraction": None,
           "d2h_bytes": offl.get("d2h_bytes"),
           "offload_wait_ms_per_step": offl.get("offload_wait_ms_per_step"),
           "peak_flops": device_peak_flops(),
           "flops_per_sec": None, "mfu": None}
    if compute_ms:
        out["flops_per_sec"] = rec["flops"] / (compute_ms / 1e3)
        if out["peak_flops"]:
            out["mfu"] = out["flops_per_sec"] / out["peak_flops"]
        if bubble_ms is not None:
            out["pp_bubble_fraction"] = bubble_ms / (bubble_ms + compute_ms)
    return out


# ---------------------------------------------------------------------------
# memory profiler (reference storage_profiler.h GpuDeviceStorageProfiler,
# enabled by the same `profile_memory` config flag the reference uses)
# ---------------------------------------------------------------------------

# The weakref finalizer (_note_free) takes NO locks: GC can run it on a
# thread that is mid-critical-section under _mlock or _lock (allocations
# inside those sections can trigger a collection), so any acquisition
# there would self-deadlock. It only appends to _pending_frees (atomic
# under the GIL); the books are settled at the next drain point
# (_note_alloc / memory_stats / render_prometheus).
_mlock = _mxsan.lock("profiler.py", "_mlock")
_mem = {
    "enabled": False,
    "live": defaultdict(int),     # device label -> live bytes
    "peak": defaultdict(int),     # device label -> peak bytes
    "buffers": {},                # id(buf) -> (nbytes, device label)
    "allocs": 0,                  # cumulative allocation events
    "frees": 0,
}
_pending_frees = []               # buffer keys enqueued by finalizers

_scope_tls = threading.local()


def _current_scope():
    stack = getattr(_scope_tls, "stack", None)
    return stack[-1] if stack else None


def memory_enabled():
    return _mem["enabled"]


def _device_of(buf):
    try:
        devs = buf.devices()
        if len(devs) == 1:
            return str(next(iter(devs)))
        return f"mesh[{len(devs)}]"
    except Exception:       # noqa: BLE001 — committed-less / host arrays
        return "uncommitted"


def _note_free(key):
    # weakref.finalize callback — must stay lock-free (see _mlock comment)
    _pending_frees.append(key)


def _drain_frees_locked():
    """Settle queued finalizer frees into the books. Caller holds _mlock.
    Returns {device: live_bytes_after} for devices that changed."""
    changed = {}
    while _pending_frees:
        try:
            key = _pending_frees.pop()
        except IndexError:      # lost a race to a concurrent drain
            break
        rec = _mem["buffers"].pop(key, None)
        if rec is None:
            continue
        nbytes, dev = rec
        _mem["live"][dev] -= nbytes
        _mem["frees"] += 1
        changed[dev] = _mem["live"][dev]
    return changed


def _drain_frees():
    with _mlock:
        changed = _drain_frees_locked()
    if changed and is_running():
        for dev, live in changed.items():
            _counter_sample(f"memory:live_bytes:{dev}", live)


def _note_alloc(buf, tag=None):
    """Account one device buffer (installed as ndarray.MEMORY_HOOK while
    profile_memory is active; also called explicitly from donation paths
    that swap raw jax buffers without constructing an NDArray). Duplicate
    registrations of the same live buffer are no-ops, so wrapper churn
    (views, out= rebinds) never double-counts."""
    if not _mem["enabled"]:
        return
    try:
        nbytes = int(buf.nbytes)
    except Exception:       # noqa: BLE001 — tracers, abstract values
        return
    key = id(buf)
    # settle queued frees first: a dead buffer's id() can be recycled by
    # this very allocation, and its stale entry would mask the new one
    _drain_frees()
    with _mlock:
        if key in _mem["buffers"]:
            return
    try:
        weakref.finalize(buf, _note_free, key)
    except TypeError:
        return              # not weakref-able: cannot track its lifetime
    dev = _device_of(buf)
    with _mlock:
        if key in _mem["buffers"]:      # lost a thread race — already in
            return
        _mem["buffers"][key] = (nbytes, dev)
        _mem["live"][dev] += nbytes
        if _mem["live"][dev] > _mem["peak"][dev]:
            _mem["peak"][dev] = _mem["live"][dev]
        _mem["allocs"] += 1
        live = _mem["live"][dev]
    if is_running():
        now = time.perf_counter() * 1e6
        scope = tag or _current_scope() or "global"
        with _lock:
            _counter_sample_locked(f"memory:live_bytes:{dev}", live)
            _events.append({"name": f"alloc:{scope}", "cat": "memory",
                            "ts": now, "dur": 0,
                            "tid": threading.get_ident(), "ph": "i",
                            "s": "t",
                            "args": {"bytes": nbytes, "device": dev}})


def memory_event(arr, tag=None):
    """Explicitly account a buffer created outside NDArray construction
    (fused-step donation outputs, sparse containers). `arr` may be an
    NDArray or a raw jax array."""
    data = getattr(arr, "_data", arr)
    _note_alloc(data, tag=tag)


def memory_stats():
    """Pure-python accounting snapshot: per-device live/peak bytes plus
    whatever the backend itself reports (jax.live_arrays byte total,
    device memory_stats) when available."""
    _drain_frees()
    with _mlock:
        snap = {
            "live_bytes": dict(_mem["live"]),
            "peak_bytes": dict(_mem["peak"]),
            "tracked_buffers": len(_mem["buffers"]),
            "alloc_events": _mem["allocs"],
            "free_events": _mem["frees"],
        }
    try:
        import jax
        snap["jax_live_bytes"] = int(sum(
            getattr(a, "nbytes", 0) for a in jax.live_arrays()))
        dev_stats = {}
        for d in jax.local_devices():
            try:
                s = d.memory_stats()
            except Exception:       # noqa: BLE001
                s = None
            if s:
                dev_stats[str(d)] = {
                    k: int(v) for k, v in s.items()
                    if k in ("bytes_in_use", "peak_bytes_in_use",
                             "bytes_limit")}
        if dev_stats:
            snap["device_memory_stats"] = dev_stats
    except Exception:       # noqa: BLE001 — no backend, headless dumps
        pass
    return snap


def _reset_memory_locked():
    """reset=True semantics: peaks collapse to the current live level and
    the event counts restart; live accounting keeps tracking the buffers
    that are still alive (dropping them would corrupt the books)."""
    with _mlock:
        _drain_frees_locked()
        for dev, live in _mem["live"].items():
            _mem["peak"][dev] = live
        _mem["allocs"] = 0
        _mem["frees"] = 0


def _exec_cache_stats(always=False):
    """Aggregate counters of the two-tier executable cache
    (compile_cache.stats()), or None when it has seen no traffic (unless
    `always`) — keeps dumps() noise-free for sessions that never jit."""
    try:
        from . import compile_cache as _cc
        snap = _cc.stats()
    except Exception:       # noqa: BLE001 — torn-down interpreter, no jax
        return None
    if not always and not any(snap.values()):
        return None
    return snap


def _tune_stats(always=False):
    """Aggregate counters of the kernel autotuner (tune.stats()), or None
    when no tuned_call site ran (unless `always`)."""
    try:
        from . import tune as _tn
        snap = _tn.stats()
    except Exception:       # noqa: BLE001 — torn-down interpreter, no jax
        return None
    if not always and not any(snap.values()):
        return None
    return snap


def _shardlint_stats(always=False):
    """Graph-capture counters (shardlint.stats(): enabled flag, buffered
    captures by kind, drops), or None when capture is off and nothing was
    ever recorded (unless `always`)."""
    try:
        from . import shardlint as _sl
        snap = _sl.stats()
    except Exception:       # noqa: BLE001 — torn-down interpreter
        return None
    if not always and not any(snap.values()):
        return None
    return snap


def _fault_stats(always=False):
    """Fault-tolerance counters (fault.stats(): checkpoints, heartbeats,
    dead/straggler sightings, rejoins), or None when the process did no
    fault-tolerance work (unless `always`)."""
    try:
        from . import fault as _ft
        snap = _ft.stats()
    except Exception:       # noqa: BLE001 — torn-down interpreter
        return None
    if not always and not any(snap.values()):
        return None
    return snap


def _fleetobs_stats(always=False):
    """Fleet-observability counters (fleetobs.stats(): snapshots built/
    folded, SLO evaluations, alert transitions, remote-profile traffic),
    or None when the plane saw no traffic (unless `always`)."""
    try:
        from . import fleetobs as _fo
        snap = _fo.stats()
    except Exception:       # noqa: BLE001 — torn-down interpreter
        return None
    if not always and not any(snap.values()):
        return None
    return snap


def _mxsan_stats(always=False):
    """Concurrency-sanitizer counters (mxsan.stats(): acquisitions
    witnessed, observed lock-order edges, blocking-under-lock sightings,
    re-entries, cycles), or None while the MXNET_MXSAN gate is off and
    nothing was recorded (unless `always`)."""
    try:
        from . import mxsan as _mx
        snap = _mx.stats()
    except Exception:       # noqa: BLE001 — torn-down interpreter
        return None
    if not always and not any(snap.values()):
        return None
    return snap


# ---------------------------------------------------------------------------
# dump / dumps
# ---------------------------------------------------------------------------

def _fold_aggregates_locked(events, counters):
    """Fold trimmed buffers into the persistent aggregates (caller holds
    _lock) so dumps() keeps whole-run stats after rolling dumps discard
    the raw events."""
    for ev in events:
        a = _agg_events.get(ev["name"])
        if a is None:
            _agg_events[ev["name"]] = [1, ev["dur"], ev["dur"], ev["dur"]]
        else:
            a[0] += 1
            a[1] += ev["dur"]
            a[2] = min(a[2], ev["dur"])
            a[3] = max(a[3], ev["dur"])
    for name, _ts, _value in counters:
        _agg_counts[name] = _agg_counts.get(name, 0) + 1


def _segment_path(seq):
    root, ext = os.path.splitext(_state["filename"])
    return f"{root}.{seq:04d}{ext or '.json'}"


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (reference MXDumpProfile;
    profiler.h:79 'chrome tracing json'). `finished=False` (the continuous
    dump path) writes a bounded *segment* file (`<name>.NNNN.json`) holding
    only the events since the previous rolling dump and clears the buffers
    — a long run produces a sequence of small traces instead of one
    ever-growing file re-serialized every period. Trimmed events are folded
    into the aggregate registry so dumps() still covers the whole run."""
    global _dump_seq
    with _lock:
        events = list(_events)
        counters = list(_counters)
        if finished:
            _events.clear()
            _counters.clear()
            _agg_events.clear()
            _agg_counts.clear()
        else:
            if not events and not counters:
                return None     # quiet period: no empty segment spam
            _events.clear()
            _counters.clear()
            _fold_aggregates_locked(events, counters)
            seq, _dump_seq = _dump_seq, _dump_seq + 1
    path = _state["filename"] if finished else _segment_path(seq)
    trace = []
    for ev in events:
        e = {"name": ev["name"], "cat": ev["cat"], "ph": ev["ph"],
             "ts": ev["ts"], "pid": 0, "tid": ev["tid"]}
        if ev["ph"] == "X":
            e["dur"] = ev["dur"]
        if "s" in ev:
            e["s"] = ev["s"]
        if "args" in ev:
            e["args"] = ev["args"]
        trace.append(e)
    for name, ts, value in counters:
        trace.append({"name": name, "ph": "C", "ts": ts, "pid": 0,
                      "args": {"value": _finite(value, 0)}})
    if attribution_enabled():
        # self clock anchor: maps this process's perf_counter trace
        # timebase onto its own wall clock, so tools/trace_merge.py can
        # place it on a shared timeline even when no peer clock_sync
        # sample exists (the server side never dials anyone)
        trace.append({"name": "clock_sync", "cat": "__metadata", "ph": "M",
                      "ts": 0, "pid": 0, "tid": 0,
                      "args": {"peer": "self", "offset_us": 0.0,
                               "rtt_us": 0.0,
                               "perf_anchor_us": time.perf_counter() * 1e6,
                               "wall_anchor_us": time.time() * 1e6,
                               "trace": trace_id()}})
    with open(path, "w") as f:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)
    return path


def _finite(v, default=None):
    """Strict-JSON guard: bare Infinity/NaN from json.dumps is rejected by
    conforming parsers; non-finite aggregates serialize as `default`."""
    if isinstance(v, float) and (v != v or v in (float("inf"),
                                                 float("-inf"))):
        return default
    return v


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate-stats string (reference MXAggregateProfileStatsPrint /
    aggregate_stats.cc). Sections:

    - per-op event table (count/total/min/max/avg us)
    - counter series (last value + sample count per name)
    - compile cache table (hits/misses/compile ms per tracked jit cache)
    - memory table (per-device live/peak bytes) when profile_memory ran

    format="json" returns the same data as a strict-JSON object (non-finite
    aggregates are null, so json.loads in strict consumers round-trips).
    """
    with _lock:
        events = list(_events)
        counters = list(_counters)
        folded = {k: list(v) for k, v in _agg_events.items()}
        folded_counts = dict(_agg_counts)
        last = dict(_counter_last)
        if reset:
            _events.clear()
            _counters.clear()
            _agg_events.clear()
            _agg_counts.clear()
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    for name, (cnt, tot, mn, mx) in folded.items():
        agg[name] = [cnt, tot, mn, mx]
    for ev in events:
        a = agg[ev["name"]]
        a[0] += 1
        a[1] += ev["dur"]
        a[2] = min(a[2], ev["dur"])
        a[3] = max(a[3], ev["dur"])
    # counter series trimmed by rolling dumps contribute their sample
    # count; the latest value comes from the gauge registry
    cagg = {name: (cnt, last.get(name, 0))
            for name, cnt in folded_counts.items()}
    for name, ts, value in counters:
        cnt = cagg[name][0] + 1 if name in cagg else 1
        cagg[name] = (cnt, value)
    comp = compile_stats()
    mem = memory_stats() if (_mem["enabled"] or _mem["allocs"]
                             or _mem["peak"]) else None
    attr = phase_stats()
    costs = cost_stats()
    mfu = mfu_stats()
    exec_cache = _exec_cache_stats()
    tune_snap = _tune_stats()
    fault_snap = _fault_stats()
    sl_snap = _shardlint_stats()
    fleet_snap = _fleetobs_stats()
    mxsan_snap = _mxsan_stats()
    if reset:
        # reset=True means reset: every stat family this dump reports
        # restarts, not just the event/counter/compile subset (the old
        # behavior left exec-cache/tune/fault/shardlint counters — and
        # their disk counters — accumulating across "reset" windows)
        with _clock:
            _compile.clear()
            _compile_warned.clear()
            _costs.clear()
        with _lock:
            _reset_phases_locked()
        _reset_memory_locked()
        try:
            from . import compile_cache as _cc
            _cc.clear(memory=False, disk=False, stats=True)
        except Exception:       # noqa: BLE001 — torn-down interpreter
            pass
        try:
            from . import tune as _tn
            _tn.clear(memory=False, stats=True)
        except Exception:       # noqa: BLE001
            pass
        try:
            from . import fault as _ft
            _ft._reset_stats()
        except Exception:       # noqa: BLE001
            pass
        try:
            from . import shardlint as _sl
            _sl.clear(stats=True)
        except Exception:       # noqa: BLE001
            pass
        try:
            from . import fleetobs as _fo
            _fo.clear(stats=True)
        except Exception:       # noqa: BLE001
            pass
        try:
            from . import mxsan as _mx
            _mx.clear(stats=True)
        except Exception:       # noqa: BLE001
            pass
    if format == "json":
        out = {
            "stats": {k: {"count": v[0], "total_us": _finite(v[1], 0.0),
                          "min_us": _finite(v[2]), "max_us": _finite(v[3])}
                      for k, v in agg.items()},
            "counters": {k: {"samples": c, "value": _finite(v)}
                         for k, (c, v) in cagg.items()},
            "compile": comp,
        }
        if attr["phases"] or attr["steps"]:
            out["step_attribution"] = {
                "steps": attr["steps"], "spans": attr["spans"],
                "phases": {p: {k: _finite(v) for k, v in rec.items()}
                           for p, rec in attr["phases"].items()}}
        if costs:
            out["cost"] = costs
        if mfu is not None:
            out["mfu"] = {k: _finite(v) for k, v in mfu.items()}
        if exec_cache is not None:
            out["exec_cache"] = exec_cache
        if tune_snap is not None:
            out["tune"] = tune_snap
        if fault_snap is not None:
            out["fault"] = fault_snap
        if sl_snap is not None:
            out["shardlint"] = sl_snap
        if fleet_snap is not None:
            out["fleetobs"] = fleet_snap
        if mxsan_snap is not None:
            out["mxsan"] = mxsan_snap
        if mem is not None:
            out["memory"] = {"live_bytes": mem["live_bytes"],
                             "peak_bytes": mem["peak_bytes"],
                             "alloc_events": mem["alloc_events"]}
        return json.dumps(out)
    lines = [f"{'Name':<40}{'Count':>8}{'Total(us)':>14}"
             f"{'Min(us)':>12}{'Max(us)':>12}{'Avg(us)':>12}",
             "-" * 98]
    key = {"total": lambda kv: kv[1][1], "count": lambda kv: kv[1][0],
           "min": lambda kv: kv[1][2], "max": lambda kv: kv[1][3],
           "avg": lambda kv: kv[1][1] / max(kv[1][0], 1)}[sort_by]
    for name, (cnt, tot, mn, mx) in sorted(agg.items(), key=key,
                                           reverse=not ascending):
        mn = 0.0 if mn == float("inf") else mn
        lines.append(f"{name:<40}{cnt:>8}{tot:>14.1f}{mn:>12.1f}"
                     f"{mx:>12.1f}{tot / max(cnt, 1):>12.1f}")
    if cagg:
        lines += ["", f"{'Counter':<48}{'Samples':>10}{'Value':>16}",
                  "-" * 74]
        for name, (cnt, val) in sorted(cagg.items()):
            sval = f"{val:.3f}" if isinstance(val, float) else f"{val}"
            lines.append(f"{name:<48}{cnt:>10}{sval:>16}")
    if attr["phases"]:
        lines += ["", f"{'Step breakdown (phase)':<28}{'Count':>8}"
                      f"{'ms/step':>12}{'Total(ms)':>12}{'Max(ms)':>12}"
                      f"{'Last(ms)':>12}",
                  "-" * 84]
        for p, rec in sorted(attr["phases"].items(),
                             key=lambda kv: -kv[1]["total_ms"]):
            lines.append(f"{p:<28}{rec['count']:>8}{rec['avg_ms']:>12.3f}"
                         f"{rec['total_ms']:>12.1f}{rec['max_ms']:>12.3f}"
                         f"{rec['last_ms']:>12.3f}")
        lines.append(f"{'(steps closed)':<28}{attr['steps']:>8}")
    if comp:
        lines += ["", f"{'Compile cache':<48}{'Hits':>8}{'Disk':>8}"
                      f"{'Misses':>8}{'Compile(ms)':>14}",
                  "-" * 86]
        for name, rec in sorted(comp.items()):
            lines.append(f"{name:<48}{rec['hits']:>8}"
                         f"{rec.get('disk_hits', 0):>8}{rec['misses']:>8}"
                         f"{rec['compile_ms']:>14.1f}")
    if costs:
        lines += ["", f"{'Compiler cost (per executable)':<48}"
                      f"{'GFLOP':>10}{'MB':>10}{'F/B':>8}",
                  "-" * 76]
        for name, rec in sorted(costs.items()):
            gf = rec.get("flops")
            mb = rec.get("bytes_accessed")
            it = rec.get("intensity")
            lines.append(
                f"{name:<48}"
                + (f"{gf / 1e9:>10.3f}" if gf else f"{'-':>10}")
                + (f"{mb / 1e6:>10.2f}" if mb else f"{'-':>10}")
                + (f"{it:>8.1f}" if it else f"{'-':>8}"))
    if mfu is not None:
        lines += ["", f"{'MFU (compiler cost / compute phase)':<48}"]
        lines.append(f"  key={mfu['key']}  "
                     f"flops/step={mfu['flops_per_step']:.3e}"
                     + (f"  compute={mfu['compute_ms_per_step']:.3f}ms"
                        if mfu["compute_ms_per_step"] else "")
                     + (f"  MFU={mfu['mfu'] * 100:.1f}%"
                        if mfu["mfu"] is not None else "  MFU=n/a"))
    if exec_cache is not None:
        lines += ["", f"{'Executable cache (two-tier)':<34}{'Value':>12}",
                  "-" * 46]
        for k in ("hits", "misses", "disk_hits", "evictions", "bytes",
                  "disk_errors", "fallbacks", "mem_entries"):
            lines.append(f"{'exec_cache_' + k:<34}{exec_cache[k]:>12}")
    if tune_snap is not None:
        lines += ["", f"{'Kernel autotuner':<34}{'Value':>12}",
                  "-" * 46]
        for k in ("searches", "hits", "disk_hits", "disk_errors",
                  "fallbacks", "winners"):
            lines.append(f"{'tune_' + k:<34}{tune_snap[k]:>12}")
    if fault_snap is not None:
        lines += ["", f"{'Fault tolerance':<34}{'Value':>12}",
                  "-" * 46]
        for k in sorted(fault_snap):
            v = fault_snap[k]
            sval = f"{v:.1f}" if isinstance(v, float) else f"{v}"
            lines.append(f"{'fault_' + k:<34}{sval:>12}")
    if sl_snap is not None:
        lines += ["", f"{'Graph capture (shardlint)':<34}{'Value':>12}",
                  "-" * 46]
        for k in ("enabled", "captures", "jit", "tuned", "partition",
                  "dropped"):
            lines.append(f"{'shardlint_' + k:<34}{sl_snap[k]:>12}")
    if fleet_snap is not None:
        lines += ["", f"{'Fleet observability (fleetobs)':<34}{'Value':>12}",
                  "-" * 46]
        for k in sorted(fleet_snap):
            lines.append(f"{'fleet_' + k:<34}{fleet_snap[k]:>12}")
    if mxsan_snap is not None:
        lines += ["", f"{'Concurrency sanitizer (mxsan)':<34}{'Value':>12}",
                  "-" * 46]
        for k in ("enabled", "records", "acquires", "edges", "blocking",
                  "reentries", "cycles", "threads", "dropped"):
            lines.append(f"{'mxsan_' + k:<34}{int(mxsan_snap[k]):>12}")
    if mem is not None and (mem["live_bytes"] or mem["peak_bytes"]):
        lines += ["", f"{'Memory (device)':<48}{'Live(bytes)':>14}"
                      f"{'Peak(bytes)':>14}",
                  "-" * 76]
        devs = sorted(set(mem["live_bytes"]) | set(mem["peak_bytes"]))
        for dev in devs:
            lines.append(f"{dev:<48}{mem['live_bytes'].get(dev, 0):>14}"
                         f"{mem['peak_bytes'].get(dev, 0):>14}")
        lines.append(f"{'(alloc events)':<48}"
                     f"{mem['alloc_events']:>14}{mem['free_events']:>14}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Prometheus text exposition (the /metrics scrape surface)
# ---------------------------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_label(value):
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def render_prometheus():
    """Serialize the live telemetry registries in Prometheus text
    exposition format (served by serve/server.py at GET /metrics):

    - every profiler Counter's last value as
      mxnet_profiler_counter{name="..."}
    - per-cache compile hits/misses/compile-time totals
    - per-device live/peak memory bytes (when profile_memory ran)
    - profiler liveness + buffered event/sample gauges
    """
    lines = []

    def family(name, mtype, help_text):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")

    family("mxnet_profiler_running", "gauge",
           "1 while the profiler is collecting")
    lines.append(f"mxnet_profiler_running {1 if is_running() else 0}")

    with _lock:
        last = dict(_counter_last)
        n_events = len(_events)
        n_samples = len(_counters)
    family("mxnet_profiler_buffered_events", "gauge",
           "trace events buffered since the last dump")
    lines.append(f"mxnet_profiler_buffered_events {n_events}")
    family("mxnet_profiler_buffered_counter_samples", "gauge",
           "counter samples buffered since the last dump")
    lines.append(f"mxnet_profiler_buffered_counter_samples {n_samples}")

    if last:
        family("mxnet_profiler_counter", "gauge",
               "last value of each profiler counter series")
        for name in sorted(last):
            val = _finite(last[name])
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                continue
            lines.append(
                f'mxnet_profiler_counter{{name="{_prom_label(name)}"}} '
                f'{val}')

    comp = compile_stats()
    if comp:
        family("mxnet_compile_cache_hits_total", "counter",
               "compiled-executable reuses per jit cache key")
        for name in sorted(comp):
            lines.append(
                f'mxnet_compile_cache_hits_total'
                f'{{key="{_prom_label(name)}"}} {comp[name]["hits"]}')
        family("mxnet_compile_cache_misses_total", "counter",
               "XLA (re)compilations per jit cache key")
        for name in sorted(comp):
            lines.append(
                f'mxnet_compile_cache_misses_total'
                f'{{key="{_prom_label(name)}"}} {comp[name]["misses"]}')
        family("mxnet_compile_cache_disk_hits_total", "counter",
               "persistent-cache deserialize hits per jit cache key "
               "(hits that a cold process would otherwise pay as "
               "recompiles)")
        for name in sorted(comp):
            lines.append(
                f'mxnet_compile_cache_disk_hits_total'
                f'{{key="{_prom_label(name)}"}} '
                f'{comp[name].get("disk_hits", 0)}')
        family("mxnet_compile_time_ms_total", "counter",
               "wall-clock ms spent tracing+compiling per jit cache key")
        for name in sorted(comp):
            lines.append(
                f'mxnet_compile_time_ms_total'
                f'{{key="{_prom_label(name)}"}} '
                f'{comp[name]["compile_ms"]:.3f}')

    with _lock:
        phase_snap = {p: (v[0], v[1], list(v[4])) for p, v in _phases.items()}
    if phase_snap:
        family("mxnet_step_phase_ms", "histogram",
               "attributed per-phase step time in ms "
               "(MXNET_STEP_ATTRIBUTION)")
        for p in sorted(phase_snap):
            cnt, total, buckets = phase_snap[p]
            lbl = _prom_label(p)
            cum = 0
            for i, b in enumerate(_PHASE_BOUNDS):
                cum += buckets[i]
                lines.append(
                    f'mxnet_step_phase_ms_bucket{{phase="{lbl}",'
                    f'le="{b:.6g}"}} {cum}')
            cum += buckets[-1]
            lines.append(
                f'mxnet_step_phase_ms_bucket{{phase="{lbl}",le="+Inf"}} '
                f'{cum}')
            lines.append(f'mxnet_step_phase_ms_sum{{phase="{lbl}"}} '
                         f'{total:.3f}')
            lines.append(f'mxnet_step_phase_ms_count{{phase="{lbl}"}} '
                         f'{cnt}')

    costs = cost_stats()
    if costs:
        _COST_FAMILIES = (
            ("flops", "mxnet_executable_flops",
             "compiler cost_analysis FLOPs per call of this executable"),
            ("bytes_accessed", "mxnet_executable_bytes_accessed",
             "compiler cost_analysis bytes accessed per call"),
            ("peak_bytes", "mxnet_executable_peak_bytes",
             "compiler memory_analysis arg+output+temp bytes"),
            ("intensity", "mxnet_executable_intensity",
             "roofline arithmetic intensity (flops per byte accessed)"),
        )
        for stat, fam, help_text in _COST_FAMILIES:
            rows = [(k, v[stat]) for k, v in sorted(costs.items())
                    if v.get(stat)]
            if not rows:
                continue
            family(fam, "gauge", help_text)
            for key, v in rows:
                lines.append(f'{fam}{{key="{_prom_label(key)}"}} {v:.6g}')
    mfu = mfu_stats()
    if mfu is not None:
        family("mxnet_model_flops_per_step", "gauge",
               "model FLOPs per train step from compiler cost accounting")
        lines.append(
            f"mxnet_model_flops_per_step {mfu['flops_per_step']:.6g}")
        if mfu["mfu"] is not None:
            family("mxnet_mfu_ratio", "gauge",
                   "model FLOP utilization from cost_analysis over the "
                   "attributed compute phase")
            lines.append(f"mxnet_mfu_ratio {mfu['mfu']:.6g}")

    ec = _exec_cache_stats(always=True)
    if ec is not None:
        _EC_FAMILIES = (
            ("hits", "counter", "exec-cache memory-tier hits"),
            ("misses", "counter", "exec-cache XLA trace+compiles"),
            ("disk_hits", "counter",
             "exec-cache persistent-tier deserialize hits"),
            ("evictions", "counter",
             "exec-cache LRU + disk-budget evictions"),
            ("bytes", "gauge", "exec-cache disk occupancy in bytes"),
            ("entries", "gauge", "exec-cache in-memory executables"),
        )
        for stat, mtype, help_text in _EC_FAMILIES:
            value = ec["mem_entries"] if stat == "entries" else ec[stat]
            suffix = "_total" if mtype == "counter" else ""
            family(f"mxnet_exec_cache_{stat}{suffix}", mtype, help_text)
            lines.append(f"mxnet_exec_cache_{stat}{suffix} {value}")

    tn = _tune_stats(always=True)
    if tn is not None:
        _TUNE_FAMILIES = (
            ("searches", "counter",
             "autotuner candidate sweeps timed (or trivially decided)"),
            ("hits", "counter", "autotuner memory-table winner lookups"),
            ("disk_hits", "counter",
             "autotuner winners re-loaded from the persistent store"),
            ("disk_errors", "counter",
             "corrupt/stale/unwritable autotuner winner files"),
            ("fallbacks", "counter",
             "tuned_call dispatches that fell back to the XLA path"),
            ("winners", "gauge", "tuned winners resident in memory"),
        )
        for stat, mtype, help_text in _TUNE_FAMILIES:
            suffix = "_total" if mtype == "counter" else ""
            family(f"mxnet_tune_{stat}{suffix}", mtype, help_text)
            lines.append(f"mxnet_tune_{stat}{suffix} {tn[stat]}")

    sl = _shardlint_stats(always=True)
    if sl is not None:
        _SL_FAMILIES = (
            ("enabled", "gauge",
             "1 while MXNET_SHARDLINT graph capture is on"),
            ("captures", "gauge",
             "shardlint captures currently buffered"),
            ("jit", "counter",
             "jaxpr captures recorded at the jit choke points"),
            ("tuned", "counter",
             "tuned_call dispatch records captured"),
            ("partition", "counter",
             "partition-rule coverage reports captured"),
            ("dropped", "counter",
             "captures evicted by the bounded buffer"),
        )
        for stat, mtype, help_text in _SL_FAMILIES:
            suffix = "_total" if mtype == "counter" else ""
            family(f"mxnet_shardlint_{stat}{suffix}", mtype, help_text)
            lines.append(f"mxnet_shardlint_{stat}{suffix} {sl[stat]}")

    ft = _fault_stats(always=True)
    if ft is not None:
        # mxnet_worker_*: the fleet-health scrape surface — liveness,
        # stragglers, elastic rejoins, and write-behind checkpoint health
        _WORKER_FAMILIES = (
            ("heartbeats_sent", "heartbeats_total", "counter",
             "liveness beats sent to the dist_async server registry"),
            ("dead_nodes_seen", "dead_nodes_total", "counter",
             "cumulative dead ranks reported by get_dead_nodes"),
            ("stragglers_seen", "stragglers_total", "counter",
             "cumulative straggler ranks reported (step lag >= "
             "MXNET_STRAGGLER_LAG)"),
            ("rejoins", "rejoins_total", "counter",
             "elastic re-registrations reclaiming a dead rank"),
            ("membership_changes", "membership_changes_total", "counter",
             "server membership epoch changes observed via heartbeats"),
            ("ckpt_saves", "checkpoint_saves_total", "counter",
             "checkpoint generations committed to disk"),
            ("ckpt_dropped", "checkpoint_dropped_total", "counter",
             "pending snapshots dropped by the bounded write-behind queue"),
            ("ckpt_errors", "checkpoint_errors_total", "counter",
             "background checkpoint write failures"),
            ("ckpt_fallbacks", "checkpoint_fallbacks_total", "counter",
             "corrupt checkpoint generations skipped at restore"),
            ("ckpt_write_ms", "checkpoint_write_ms_total", "counter",
             "wall-clock ms spent writing checkpoints off the step path"),
            ("ckpt_last_step", "checkpoint_last_step", "gauge",
             "newest step durably checkpointed"),
            ("faults_injected", "faults_injected_total", "counter",
             "MXNET_FAULT_INJECT actions fired (tests only)"),
            ("slo_alerts", "slo_alerts_total", "counter",
             "fleet SLO alerts raised by the fleetobs burn-rate engine"),
        )
        for stat, prom, mtype, help_text in _WORKER_FAMILIES:
            family(f"mxnet_worker_{prom}", mtype, help_text)
            v = ft[stat]
            v = f"{v:.3f}" if isinstance(v, float) else f"{v}"
            lines.append(f"mxnet_worker_{prom} {v}")

    # mxnet_mxsan_*: the concurrency-sanitizer surface. mxsan renders
    # its own block and returns "" until the first record, so a gate-off
    # scrape stays byte-identical to a build without the sanitizer.
    try:
        from . import mxsan as _mx
        san = _mx.render_prometheus().rstrip("\n")
    except Exception:       # noqa: BLE001 — torn-down interpreter
        san = ""
    if san:
        lines.append(san)

    _drain_frees()
    with _mlock:
        live = dict(_mem["live"])
        peak = dict(_mem["peak"])
    if live or peak:
        family("mxnet_memory_live_bytes", "gauge",
               "python-accounted live device bytes (profile_memory)")
        for dev in sorted(live):
            lines.append(
                f'mxnet_memory_live_bytes{{device="{_prom_label(dev)}"}} '
                f'{live[dev]}')
        family("mxnet_memory_peak_bytes", "gauge",
               "python-accounted peak device bytes (profile_memory)")
        for dev in sorted(peak):
            lines.append(
                f'mxnet_memory_peak_bytes{{device="{_prom_label(dev)}"}} '
                f'{peak[dev]}')

    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# user objects: Domain / Scope / Task / Event / Marker / Counter
# ---------------------------------------------------------------------------

class Domain:
    """Named grouping for Tasks/Counters/Markers (reference profiler.py
    Domain / MXProfileCreateDomain): events carry the domain as their
    chrome-trace category, so traces group per domain."""

    def __init__(self, name):
        self.name = str(name)

    def new_task(self, name="task"):
        return Task(self, name)

    def new_counter(self, name="counter", value=None):
        return Counter(self, name, value)

    def new_marker(self, name="marker"):
        return Marker(self, name)

    def __repr__(self):
        return f"Domain({self.name!r})"


def _domain_name(domain):
    if domain is None:
        return None
    return getattr(domain, "name", str(domain))


class _Timed:
    """Scoped marker base (reference profiler.py Task/Event/Frame)."""

    def __init__(self, name, category):
        self._name = name
        self._category = category
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is None:
            return
        dur = (time.perf_counter() - self._t0) * 1e6
        _record(self._name, self._category, self._t0 * 1e6, dur)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Scope(_Timed):
    """Named scope; while active it also tags memory-allocation events on
    this thread (the reference's profiler scope strings in
    storage_profiler alloc names)."""

    def __init__(self, name="<unk>:"):
        super().__init__(name, "scope")

    def start(self):
        super().start()
        stack = getattr(_scope_tls, "stack", None)
        if stack is None:
            stack = _scope_tls.stack = []
        stack.append(self._name)

    def stop(self):
        stack = getattr(_scope_tls, "stack", None)
        if stack and stack[-1] == self._name:
            stack.pop()
        super().stop()


class Task(_Timed):
    def __init__(self, domain=None, name="task"):
        dom = _domain_name(domain)
        super().__init__(name, dom if dom else "task")


class Event(_Timed):
    def __init__(self, name="event"):
        super().__init__(name, "event")


_MARK_SCOPES = {"process": "p", "thread": "t", "global": "g"}


class Marker:
    """Instant marker (reference profiler.py Marker.mark): `ph:"i"` with
    the chrome instant-scope flag derived from mark(scope=...)."""

    def __init__(self, domain=None, name="marker"):
        self._name = name
        self._category = _domain_name(domain) or "marker"

    def mark(self, scope="process"):
        _record(self._name, self._category, time.perf_counter() * 1e6, 0,
                ph="i", scope=_MARK_SCOPES.get(scope, "t"))


class Counter:
    """Numeric counter series (reference profiler.py Counter). increment/
    decrement are atomic: the read-modify-write happens under the module
    lock, so concurrent bumps from serve/batcher threads never lose
    updates."""

    def __init__(self, domain=None, name="counter", value=None):
        dom = _domain_name(domain)
        self._name = f"{dom}::{name}" if dom else name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        with _lock:
            self._value = value
            _counter_sample_locked(self._name, value)

    def increment(self, delta=1):
        with _lock:
            self._value += delta
            _counter_sample_locked(self._name, self._value)

    def decrement(self, delta=1):
        self.increment(-delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self
