"""Graph-capture registry for the shardlint analyzer (tools/shardlint).

mxlint (PR 4) sees Python AST; the bugs that cost MFU at scale — silent
full replication, implicit cross-device transfers, f64 promotion, missed
donation, host callbacks inside a hot step — only appear in the *lowered*
program. This module is the package-side half of the analyzer: a bounded
registry of `Capture` records snapshotted at the jit choke points the
framework already owns (`compile_cache.cached_jit`, `profiler.track_jit`,
`tune.tuned_call`) plus the partition-rule matcher
(`parallel.partition.match_partition_rules`).

Capture is OFF by default (`MXNET_SHARDLINT`); when off every hook is a
cached boolean check on a path that already runs at most once per call
signature, so steady-state training and serving pay nothing (asserted by
tests/test_shardlint.py). The rule passes themselves (SL01-SL05) live in
tools/shardlint and never import from here at package import time.

Call sites that know what their arguments *mean* declare it with
`annotate(key, arg_roles=..., declared_bf16=...)`; the donation audit
(SL03) and mixed-precision rule (SL02) only judge what a call site has
explicitly declared, so un-annotated user jits are never false positives.
"""
from __future__ import annotations

import threading
from . import mxsan as _mxsan

__all__ = ["Capture", "enabled", "enable", "reset", "annotate",
           "annotation_for", "record_jit", "record_tuned",
           "record_partition", "trace_capture", "partition_capture",
           "captures", "clear", "stats"]

# Guards the capture buffer, counters, and annotation table
# (declared in tools/mxlint/lock_order.py).
_lock = _mxsan.lock("shardlint.py", "_lock")
_captures = []
_annotations = {}            # jit key -> metadata dict
_stats = {
    "jit": 0,                # jaxpr captures at cached_jit/track_jit
    "tuned": 0,              # tuned_call dispatch records
    "partition": 0,          # partition-rule coverage records
    "dropped": 0,            # captures evicted by the bounded buffer
}
_enabled = None              # cached MXNET_SHARDLINT read; None = unread


class Capture:
    """One graph-level observation for the rule passes.

    kind is "jit" (a traced program: `jaxpr` is the ClosedJaxpr),
    "tuned" (a tuned_call dispatch: metadata only, args may be tracers),
    or "partition" (a partition-rule coverage report: `meta` holds
    leaves/matched/unmatched/replicated name lists).
    """

    __slots__ = ("key", "kind", "jaxpr", "donate_argnums", "arg_roles",
                 "declared_bf16", "donation_supported", "backend",
                 "lowered_text", "allgather_budget", "meta")

    def __init__(self, key, kind="jit", jaxpr=None, donate_argnums=(),
                 arg_roles=None, declared_bf16=False,
                 donation_supported=False, backend="unknown",
                 lowered_text=None, allgather_budget=None, meta=None):
        self.key = key
        self.kind = kind
        self.jaxpr = jaxpr
        self.donate_argnums = tuple(donate_argnums or ())
        self.arg_roles = dict(arg_roles) if arg_roles else None
        self.declared_bf16 = bool(declared_bf16)
        self.donation_supported = bool(donation_supported)
        self.backend = backend
        self.lowered_text = lowered_text
        self.allgather_budget = allgather_budget
        self.meta = dict(meta) if meta else {}

    def __repr__(self):
        return f"Capture({self.key!r}, kind={self.kind!r})"


# ---------------------------------------------------------------------------
# the on/off gate
# ---------------------------------------------------------------------------

def enabled():
    """True when graph capture is on. The env var is read once and the
    answer cached — the hooks sit on trace paths but must stay free."""
    global _enabled
    if _enabled is None:
        from .util import getenv_bool
        _enabled = getenv_bool("MXNET_SHARDLINT")
    return _enabled


def enable(on=True):
    """Force capture on/off for this process (tests, the offline CLI);
    returns the previous effective state."""
    global _enabled
    prev = enabled()
    _enabled = bool(on)
    return prev


def reset():
    """Forget the cached MXNET_SHARDLINT read and drop all state — the
    next `enabled()` consults the environment again."""
    global _enabled
    _enabled = None
    clear(stats=True)


def _cap_max():
    from .util import getenv_int
    return max(getenv_int("MXNET_SHARDLINT_CAPTURES"), 1)


# ---------------------------------------------------------------------------
# call-site metadata
# ---------------------------------------------------------------------------

def annotate(key, arg_roles=None, declared_bf16=None, allgather_budget=None):
    """Declare what a jit key's arguments mean. `arg_roles` maps positional
    argnum -> one of "params" / "opt_state" / "weights" (donation-eligible),
    "grads" (must NOT be donated), "weights_shared" (reused across calls,
    never donated), "rng" / "step" / "data" (neutral). `declared_bf16`
    marks the program as an intentional-bf16 region for SL02;
    `allgather_budget` caps all-gathers counted on lowered modules (SL05).
    Annotation is unconditional (construction-time, not per-call) so a
    capture recorded after a later enable() still finds it."""
    with _lock:
        entry = _annotations.setdefault(key, {})
        if arg_roles is not None:
            entry["arg_roles"] = dict(arg_roles)
        if declared_bf16 is not None:
            entry["declared_bf16"] = bool(declared_bf16)
        if allgather_budget is not None:
            entry["allgather_budget"] = int(allgather_budget)


def annotation_for(key):
    with _lock:
        entry = _annotations.get(key)
        return dict(entry) if entry else {}


def _donation_supported():
    # single source of truth for "does this backend alias buffers"
    from .ops.optimizer_ops import _donation_supported as ds
    try:
        return ds()
    except Exception:       # noqa: BLE001 — no jax backend yet
        return False


def _backend():
    from .compile_cache import _backend as bk
    try:
        return bk()
    except Exception:       # noqa: BLE001
        return "unknown"


def _push(cap, counter):
    cap_max = _cap_max()
    with _lock:
        _captures.append(cap)
        _stats[counter] += 1
        while len(_captures) > cap_max:
            _captures.pop(0)
            _stats["dropped"] += 1


# ---------------------------------------------------------------------------
# recorders (the choke-point hooks call these; all gated on enabled())
# ---------------------------------------------------------------------------

def record_jit(key, traced=None, jaxpr=None, donate_argnums=(),
               lowered_text=None):
    """Record one traced program. `traced` is what `jax.jit(fn).trace(...)`
    returns; its `.jaxpr` is snapshotted. Never raises — a capture failure
    must not break the compile path it observes."""
    if not enabled():
        return None
    try:
        if jaxpr is None and traced is not None:
            jaxpr = traced.jaxpr
        ann = annotation_for(key)
        cap = Capture(
            key, kind="jit", jaxpr=jaxpr,
            donate_argnums=donate_argnums,
            arg_roles=ann.get("arg_roles"),
            declared_bf16=ann.get("declared_bf16", False),
            donation_supported=_donation_supported(),
            backend=_backend(),
            lowered_text=lowered_text,
            allgather_budget=ann.get("allgather_budget"))
        _push(cap, "jit")
        return cap
    except Exception:       # noqa: BLE001 — observation must be free of risk
        return None


def record_tuned(kernel, call_key):
    """Record one tuned_call dispatch. Metadata only: tuned_call runs
    inside traces where the args are tracers, so nothing value-dependent
    is touched here."""
    if not enabled():
        return None
    cap = Capture(f"tuned:{kernel}", kind="tuned",
                  meta={"call_key": call_key})
    _push(cap, "tuned")
    return cap


def record_partition(key, leaves, matched, unmatched, replicated,
                     rules=None):
    """Record one partition-rule coverage report: every leaf name with how
    it resolved (matched rule pattern, explicitly replicated, or UNMATCHED
    — SL04's error case)."""
    if not enabled():
        return None
    cap = partition_capture(key, leaves, matched, unmatched, replicated,
                            rules=rules)
    _push(cap, "partition")
    return cap


# ---------------------------------------------------------------------------
# direct builders (fixtures / tests / offline corpus)
# ---------------------------------------------------------------------------

def trace_capture(fn, *args, key="fixture", donate_argnums=(),
                  arg_roles=None, declared_bf16=False,
                  donation_supported=None, lowered_text=None,
                  allgather_budget=None, **kwargs):
    """Trace `fn(*args, **kwargs)` with jax.jit and build a Capture
    directly, bypassing the enable gate — the fixture-corpus helper.
    `donate_argnums`/`arg_roles` here are *claims* for the rule passes,
    so SL03 scenarios are testable on CPU."""
    import jax
    traced = jax.jit(fn).trace(*args, **kwargs)
    if donation_supported is None:
        donation_supported = _donation_supported()
    return Capture(key, kind="jit", jaxpr=traced.jaxpr,
                   donate_argnums=donate_argnums, arg_roles=arg_roles,
                   declared_bf16=declared_bf16,
                   donation_supported=donation_supported,
                   backend=_backend(), lowered_text=lowered_text,
                   allgather_budget=allgather_budget)


def partition_capture(key, leaves, matched, unmatched, replicated,
                      rules=None):
    """Build a partition-coverage Capture directly (no enable gate)."""
    return Capture(key, kind="partition", meta={
        "leaves": list(leaves),
        "matched": dict(matched),
        "unmatched": list(unmatched),
        "replicated": list(replicated),
        "rules": [str(r) for r in (rules or ())],
    })


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------

def captures():
    """Snapshot of the capture buffer (oldest first)."""
    with _lock:
        return list(_captures)


def clear(stats=False):
    """Drop buffered captures (and optionally zero the counters). The
    annotation table survives: it is construction-time declaration, not
    per-run observation."""
    with _lock:
        _captures.clear()
        if stats:
            for k in _stats:
                _stats[k] = 0


def stats():
    """Counter snapshot (the `shardlint_*` telemetry surface in
    profiler.dumps() and /metrics): enabled flag, buffered captures,
    per-kind record counts, drops."""
    with _lock:
        snap = dict(_stats)
        snap["captures"] = len(_captures)
    snap["enabled"] = 1 if (_enabled is True) else 0
    return snap
