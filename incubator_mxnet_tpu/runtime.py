"""Runtime feature detection.

Reference: python/mxnet/runtime.py:57 `feature_list()` over
src/libinfo.cc:103-121 compiled-feature bits — tests and user code gate on
what the build supports. Here features are probed from the live jax
runtime (device kinds, dtypes, pallas availability) instead of compile
flags.
"""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list",
           "set_fp32_matmul_mode", "fp32_matmul_mode"]

# fp32 matmul/conv execution mode -> jax matmul precision. "strict" is
# the default (reference fp32 semantics: full-precision accumulate);
# "fast" runs fp32 dots as three bf16 passes on the MXU (~1e-6 relative
# error, several-fold faster on TPU); "fastest" is one bf16 pass
# (bf16-level error, full MXU rate). bf16 inputs are unaffected —
# this only governs what a float32 x float32 dot means.
_FP32_MODES = {"strict": "highest", "fast": "high", "fastest": "default"}
_fp32_mode = "strict"


def set_fp32_matmul_mode(mode):
    """Select fp32 matmul semantics ("strict" | "fast" | "fastest");
    also settable at import via MXTPU_FP32_MATMUL. Applies process-wide
    (jax_default_matmul_precision); already-compiled executables are
    unaffected until retraced."""
    global _fp32_mode
    mode = (mode or "strict").lower()
    if mode not in _FP32_MODES:
        raise ValueError(f"fp32 matmul mode must be one of "
                         f"{sorted(_FP32_MODES)}, got {mode!r}")
    import jax
    jax.config.update("jax_default_matmul_precision", _FP32_MODES[mode])
    _fp32_mode = mode


def fp32_matmul_mode():
    return _fp32_mode


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _probe():
    import jax
    import jax.numpy as jnp

    feats = {}
    try:
        devs = jax.devices()
    except Exception:
        devs = []
    kinds = {d.platform for d in devs}
    feats["TPU"] = "tpu" in kinds or any(
        "tpu" in str(getattr(d, "device_kind", "")).lower() for d in devs)
    feats["CUDA"] = "gpu" in kinds or "cuda" in kinds
    feats["CPU"] = True
    feats["BF16"] = True  # bfloat16 is first-class in jax on every backend
    feats["F16C"] = True
    feats["INT64_TENSOR_SIZE"] = jax.config.jax_enable_x64
    try:
        from jax.experimental import pallas  # noqa: F401
        feats["PALLAS"] = True
    except Exception:
        feats["PALLAS"] = False
    feats["X64"] = jax.config.jax_enable_x64
    feats["DIST_KVSTORE"] = True  # kvstore.py rides mesh collectives
    feats["OPENMP"] = False  # XLA owns threading; no OMP pools (SURVEY §1 L2)
    feats["SIGNAL_HANDLER"] = True
    feats["PROFILER"] = True
    feats["COMPILATION_CACHE"] = bool(jax.config.jax_compilation_cache_dir)
    return feats


class Features(dict):
    """Reference runtime.py Features: mapping name -> Feature with
    is_enabled."""

    def __init__(self):
        super().__init__([(k, Feature(k, v)) for k, v in _probe().items()])

    def is_enabled(self, feature_name):
        if feature_name not in self:
            from .base import MXNetError
            raise MXNetError(f"unknown feature {feature_name!r} "
                             f"(known: {sorted(self)})")
        return self[feature_name].enabled

    def __repr__(self):
        return str(list(self.values()))


def feature_list():
    """Reference runtime.py:57."""
    return list(Features().values())
