"""AOT compilation + persistent executable cache (ROADMAP item 4).

PR 3's `track_jit` made XLA recompiles *observable* at the four choke
points the framework owns (op registry fwd/vjp, fused optimizer dispatch,
kvstore flat-pack, serving executables) — this module makes them
*avoidable*. `cached_jit(key, fn)` is a drop-in replacement for
`track_jit(key, jax.jit(fn))` that routes every call through one shared
two-tier executable cache:

- **memory tier**: a process-wide LRU (`MXNET_EXEC_CACHE_SIZE` entries)
  over AOT-compiled executables, unifying the four ad-hoc caches (serve's
  per-bucket dict that hard-failed when full, the op registry's fwd/vjp
  memo, `optimizer_ops._fused_cache`, kvstore's flat-pack lru_cache) under
  ONE eviction policy;
- **disk tier** (`MXNET_EXEC_CACHE_DIR`, empty = disabled): executables
  are serialized through `jax.experimental.serialize_executable` and keyed
  by a stable content fingerprint, so a *fresh process* deserializes in
  milliseconds instead of re-tracing + re-compiling — a serving fleet
  cold-starts in seconds (PAPERS.md: "Automatic Full Compilation … to
  Cloud TPUs" serialized AOT executables; TVM persisted tuned artifacts
  keyed by shape/dtype).

The fingerprint covers everything that can invalidate an executable:
the traced jaxpr text + closure-captured constants, abstract arg
shapes/dtypes/weak-types and shardings, the jit options (donation), the
cache key, jax version, backend, and device kind/count.  Python's builtin
`hash()` is per-process salted and never used.  A disk entry whose
fingerprint, checksum, or deserialization disagrees is deleted and treated
as a miss — corruption, version skew, or backend mismatch degrade to a
plain recompile, never a crash, never a stale executable.

Telemetry: every lookup reports through `profiler.compile_event` (so the
compile table distinguishes memory hits / disk deserialize-hits / true XLA
retraces), and aggregate `exec_cache_{hits,misses,disk_hits,evictions,
bytes}` counters surface in `profiler.dumps()` and `render_prometheus()`.

This cache is complementary to jax's own persistent *compilation* cache
(`MXTPU_COMPILE_CACHE`, configured in `__init__._configure_jax`): that one
still pays tracing + lowering + cache-key hashing per process; this one
skips straight from abstract shapes to a loaded executable.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from . import mxsan as _mxsan
import time
from collections import OrderedDict

__all__ = ["cached_jit", "stats", "clear", "disk_stats"]

_MAGIC = b"MXEC1\n"          # on-disk format: MAGIC + fp + "\n" + sha + "\n" + body
_SUFFIX = ".mxec"
_CONST_HASH_BYTES = 1 << 20  # consts larger than this hash by shape/dtype only
_SIG_MEMO_MAX = 512          # per-wrapper signature->fingerprint memo bound

# Module lock guards the LRU + counters (declared in tools/mxlint/lock_order.py).
_lock = _mxsan.lock("compile_cache.py", "_lock")
_mem = OrderedDict()         # fingerprint -> loaded executable (LRU)
_stats = {
    "hits": 0,               # memory-tier hits
    "misses": 0,             # true XLA trace+compile
    "disk_hits": 0,          # fresh-process deserialize instead of compile
    "evictions": 0,          # memory LRU + disk budget evictions
    "bytes": 0,              # disk occupancy (refreshed on writes/scans)
    "disk_errors": 0,        # corrupt/unreadable/unserializable entries
    "fallbacks": 0,          # AOT machinery failed; plain jit served the call
}
_disk_scanned = False        # lazily refresh "bytes" once per process
_warned = set()


# ---------------------------------------------------------------------------
# knobs (registered in util.ENV_VARS; mxlint EV01/EV02 police raw reads)
# ---------------------------------------------------------------------------

def _cache_dir():
    from .util import getenv_str
    d = getenv_str("MXNET_EXEC_CACHE_DIR")
    return os.path.expanduser(d) if d else None


def _mem_cap():
    from .util import getenv_int
    return max(getenv_int("MXNET_EXEC_CACHE_SIZE"), 1)


def _disk_budget():
    from .util import getenv_int
    return getenv_int("MXNET_EXEC_CACHE_DISK_BYTES")


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _jax_version():
    import jax
    return str(jax.__version__)


def _backend():
    import jax
    try:
        return jax.default_backend()
    except Exception:       # noqa: BLE001 — no backend yet
        return "unknown"


def _device_kind():
    import jax
    try:
        devs = jax.local_devices()
        return f"{devs[0].device_kind}x{len(devs)}"
    except Exception:       # noqa: BLE001
        return "unknown"


def _default_device():
    import jax
    try:
        return jax.local_devices()[0]
    except Exception:       # noqa: BLE001
        return None


def _leaf_sig(x):
    """Hashable abstract signature of one call-argument leaf."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        # python scalar / bool: jit traces these as weak-typed leaves whose
        # jaxpr is value-independent, so the type alone identifies them
        return ("py", type(x).__name__)
    weak = getattr(x, "weak_type", None)
    if weak is None:
        weak = getattr(getattr(x, "aval", None), "weak_type", False)
    sh = getattr(x, "sharding", None)
    if sh is not None:
        try:
            from jax.sharding import SingleDeviceSharding
            if isinstance(sh, SingleDeviceSharding) and \
                    next(iter(sh.device_set)) == _default_device():
                # an uncommitted array on the default device traces the
                # same as a ShapeDtypeStruct with no sharding: normalize
                # so Predictor.warmup() avals match real-traffic calls
                sh = None
        except Exception:       # noqa: BLE001 — exotic sharding objects
            sh = repr(sh)
    return (tuple(shape), str(dtype), bool(weak), sh)


def _call_sig(args, kwargs):
    import jax
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_sig(x) for x in leaves))


def _fingerprint(key, opts_repr, traced, sig):
    """Stable hex digest identifying one compiled executable across
    processes. sha256 throughout — builtin hash() is per-process salted."""
    import numpy as np
    h = hashlib.sha256()
    for part in ("mxec1", _jax_version(), _backend(), _device_kind(),
                 key, opts_repr, str(sig[0]), repr(sig[1])):
        h.update(part.encode())
        h.update(b"\x00")
    closed = traced.jaxpr
    # the jaxpr text elides closure-captured constant *values*; hash them
    # separately or a changed baked-in table would collide (TS04's hazard)
    h.update(str(closed).encode())
    for c in getattr(closed, "consts", ()):
        try:
            a = np.asarray(c)
            h.update(repr((tuple(a.shape), str(a.dtype))).encode())
            if a.nbytes <= _CONST_HASH_BYTES:
                h.update(a.tobytes())
        except Exception:       # noqa: BLE001 — non-array consts
            h.update(repr(c).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# memory tier (process-wide LRU)
# ---------------------------------------------------------------------------

def _mem_get(fp):
    with _lock:
        exe = _mem.get(fp)
        if exe is not None:
            _mem.move_to_end(fp)
    return exe


def _mem_put(fp, exe):
    cap = _mem_cap()
    with _lock:
        _mem[fp] = exe
        _mem.move_to_end(fp)
        while len(_mem) > cap:
            _mem.popitem(last=False)
            _stats["evictions"] += 1


# ---------------------------------------------------------------------------
# disk tier
# ---------------------------------------------------------------------------

def _entry_path(d, fp):
    return os.path.join(d, fp + _SUFFIX)


def _disk_load(fp):
    """Deserialize one disk entry, or None (missing / corrupt / stale —
    never raises). A bad entry is deleted so it cannot be retried."""
    d = _cache_dir()
    if not d:
        return None
    path = _entry_path(d, fp)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None             # plain miss: no entry
    try:
        if not raw.startswith(_MAGIC):
            raise ValueError("bad magic")
        off = len(_MAGIC)
        stored_fp = raw[off:off + 64].decode("ascii")
        sha = raw[off + 65:off + 129].decode("ascii")
        body = raw[off + 130:]
        if stored_fp != fp:
            raise ValueError("fingerprint mismatch")
        if hashlib.sha256(body).hexdigest() != sha:
            raise ValueError("checksum mismatch")
        payload, in_tree, out_tree = pickle.loads(body)
        from jax.experimental import serialize_executable as _se
        return _se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as exc:    # noqa: BLE001 — corruption/skew degrade
        with _lock:
            _stats["disk_errors"] += 1
            warn = path not in _warned
            _warned.add(path)
        if warn:
            logging.warning(
                "compile_cache: dropping unusable disk entry %s (%s); "
                "recompiling", path, exc)
        try:
            os.remove(path)
        except OSError:
            pass
        return None


def _disk_store(fp, exe):
    """Best-effort serialize + atomic publish (os.replace): two processes
    racing on the same key each write a private tmp file and the last
    rename wins — readers only ever see a complete entry."""
    d = _cache_dir()
    if not d:
        return False
    try:
        from jax.experimental import serialize_executable as _se
        payload, in_tree, out_tree = _se.serialize(exe)
        body = pickle.dumps((payload, in_tree, out_tree))
    except Exception:           # noqa: BLE001 — host callbacks, old jax
        with _lock:
            _stats["disk_errors"] += 1
        return False
    blob = (_MAGIC + fp.encode("ascii") + b"\n"
            + hashlib.sha256(body).hexdigest().encode("ascii") + b"\n" + body)
    path = _entry_path(d, fp)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(d, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError:
        with _lock:
            _stats["disk_errors"] += 1
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    _enforce_disk_budget(d)
    return True


def _scan_dir(d):
    """[(path, mtime, size)] of cache entries, oldest first."""
    entries = []
    try:
        names = os.listdir(d)
    except OSError:
        return entries
    for name in names:
        if not name.endswith(_SUFFIX):
            continue
        path = os.path.join(d, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        entries.append((path, st.st_mtime, st.st_size))
    entries.sort(key=lambda e: e[1])
    return entries


def _enforce_disk_budget(d):
    """Evict oldest entries while occupancy exceeds
    MXNET_EXEC_CACHE_DISK_BYTES (<=0 disables the bound)."""
    global _disk_scanned
    budget = _disk_budget()
    entries = _scan_dir(d)
    total = sum(size for _, _, size in entries)
    evicted = 0
    if budget > 0:
        for path, _mtime, size in entries:
            if total <= budget:
                break
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            evicted += 1
    with _lock:
        _stats["bytes"] = total
        _stats["evictions"] += evicted
        _disk_scanned = True


# ---------------------------------------------------------------------------
# the wrapper
# ---------------------------------------------------------------------------

class _CachedJit:
    """Callable wrapping `jax.jit(fn, **jit_kwargs)` behind the two-tier
    executable cache. Signature-compatible with what `track_jit` returned
    (`__wrapped__`, `_compile_key`), plus `.warmup()` for AOT pre-warming.
    """

    def __init__(self, key, fn, **jit_kwargs):
        import jax
        from . import profiler as _prof
        self._key = key
        self._compile_key = key
        self._fn = fn
        self.__wrapped__ = fn
        self._jfn = jax.jit(fn, **jit_kwargs)
        self._opts = repr(sorted(jit_kwargs.items()))
        self._donate = tuple(jit_kwargs.get("donate_argnums", ()) or ())
        # plain-jit escape hatch: anything the AOT path cannot serve
        # (tracer args, exotic leaves, executable/aval skew) runs here,
        # keeping track_jit's probe-based accounting for those calls
        self._fallback = _prof.track_jit(key, self._jfn)
        self._lock = _mxsan.lock(
            "compile_cache.py", "self._lock")           # guards _fps memo
        self._compile_lock = _mxsan.lock(
            "compile_cache.py", "self._compile_lock")   # single-flight compiles
        self._fps = OrderedDict()               # call sig -> fingerprint

    # -- internals ------------------------------------------------------
    def _fingerprint_for(self, args, kwargs):
        """(fingerprint, traced-or-None) for one call signature."""
        sig = _call_sig(args, kwargs)
        with self._lock:
            fp = self._fps.get(sig)
        if fp is not None:
            return fp, None
        traced = self._jfn.trace(*args, **kwargs)
        # shardlint graph capture: this branch runs once per call
        # signature per process, so the observation is free when off and
        # a single snapshot when on
        from . import shardlint as _sl
        if _sl.enabled():
            _sl.record_jit(self._key, traced=traced,
                           donate_argnums=self._donate)
        fp = _fingerprint(self._key, self._opts, traced, sig)
        with self._lock:
            while len(self._fps) >= _SIG_MEMO_MAX:
                self._fps.popitem(last=False)
            self._fps[sig] = fp
        return fp, traced

    def _ensure(self, args, kwargs):
        """Executable for this call signature: (exe, kind, ms) where kind
        is "hit" (memory), "disk" (deserialized), or "miss" (XLA
        compiled). Tracing for the fingerprint is shared with compiling —
        a cold call traces exactly once."""
        fp, traced = self._fingerprint_for(args, kwargs)
        exe = _mem_get(fp)
        if exe is not None:
            with _lock:
                _stats["hits"] += 1
            return exe, "hit", 0.0
        with self._compile_lock:
            exe = _mem_get(fp)
            if exe is not None:
                with _lock:
                    _stats["hits"] += 1
                return exe, "hit", 0.0
            t0 = time.perf_counter()
            exe = _disk_load(fp)
            if exe is not None:
                _mem_put(fp, exe)
                with _lock:
                    _stats["disk_hits"] += 1
                self._note_cost(exe)
                return exe, "disk", (time.perf_counter() - t0) * 1e3
            if traced is None:
                traced = self._jfn.trace(*args, **kwargs)
            exe = traced.lower().compile()
            ms = (time.perf_counter() - t0) * 1e3
            with _lock:
                _stats["misses"] += 1
            _mem_put(fp, exe)
            _disk_store(fp, exe)
            self._note_cost(exe)
            return exe, "miss", ms

    def _note_cost(self, exe):
        """Compiler cost accounting: record cost_analysis() /
        memory_analysis() for every executable this cache acquires (fresh
        compile or disk deserialize) into the profiler's per-key cost
        table. Gated on the attribution flag like every automatic
        observability hook — otherwise every op a process ever compiles
        leaks into dumps() (callers who want cost unconditionally use
        profiler.cost_from_executable directly, the bench.py path).
        Never raises — cost extraction is advisory."""
        try:
            from . import profiler as _prof
            if not _prof.attribution_enabled():
                return
            _prof.cost_from_executable(self._key, exe)
        except Exception:       # noqa: BLE001 — torn-down interpreter
            pass

    def _note_fallback(self):
        with _lock:
            _stats["fallbacks"] += 1
            warn = self._key not in _warned
            _warned.add(self._key)
        if warn:
            logging.info(
                "compile_cache: key %r served by plain jit fallback "
                "(argument signature outside the AOT path)", self._key)

    # -- public surface -------------------------------------------------
    def __call__(self, *args, **kwargs):
        from . import profiler as _prof
        try:
            exe, kind, ms = self._ensure(args, kwargs)
        except Exception:       # noqa: BLE001 — tracers/odd leaves
            self._note_fallback()
            return self._fallback(*args, **kwargs)
        _prof.compile_event(self._key, cache_hit=(kind != "miss"),
                            compile_ms=ms, disk=(kind == "disk"))
        try:
            return exe(*args, **kwargs)
        except Exception:       # noqa: BLE001 — aval/layout skew at call
            self._note_fallback()
            return self._fallback(*args, **kwargs)

    def trace_signature(self, *args, **kwargs):
        """Trace (but do NOT compile) this call signature, returning its
        fingerprint. Cheap way to materialize the jaxpr for one signature
        — the shardlint offline corpus uses it to feed the capture hook
        without paying an XLA compile. Args may be concrete arrays or
        `jax.ShapeDtypeStruct` avals."""
        fp, _traced = self._fingerprint_for(args, kwargs)
        return fp

    def warmup(self, *args, **kwargs):
        """Materialize the executable for this signature WITHOUT running
        it: args may be concrete arrays or `jax.ShapeDtypeStruct` avals.
        Returns "hit" / "disk" / "miss" — a warm fleet sees "disk"."""
        from . import profiler as _prof
        exe, kind, ms = self._ensure(args, kwargs)
        del exe
        _prof.compile_event(self._key, cache_hit=(kind != "miss"),
                            compile_ms=ms, disk=(kind == "disk"))
        return kind

    def __repr__(self):
        return f"cached_jit({self._key!r})"


def cached_jit(key, fn, **jit_kwargs):
    """Wrap `fn` as a jitted callable served from the two-tier executable
    cache, reporting per-call hit/disk-hit/retrace telemetry under `key`
    (same key namespace as `profiler.track_jit`)."""
    return _CachedJit(key, fn, **jit_kwargs)


# ---------------------------------------------------------------------------
# introspection / management
# ---------------------------------------------------------------------------

def stats():
    """Aggregate counter snapshot (the `exec_cache_*` telemetry surface):
    hits, misses, disk_hits, evictions, bytes (disk occupancy),
    disk_errors, fallbacks, mem_entries."""
    d = _cache_dir()
    if d and not _disk_scanned:
        disk_stats()            # refresh "bytes" once for warm processes
    with _lock:
        snap = dict(_stats)
        snap["mem_entries"] = len(_mem)
    return snap


def disk_stats():
    """Occupancy snapshot of the disk tier: {dir, entries, bytes, budget}.
    Also refreshes the `bytes` aggregate counter."""
    global _disk_scanned
    d = _cache_dir()
    if not d:
        return {"dir": None, "entries": 0, "bytes": 0,
                "budget": _disk_budget()}
    entries = _scan_dir(d)
    total = sum(size for _, _, size in entries)
    with _lock:
        _stats["bytes"] = total
        _disk_scanned = True
    return {"dir": d, "entries": len(entries), "bytes": total,
            "budget": _disk_budget()}


def clear(memory=True, disk=False, stats=False):
    """Drop cache state. `memory=True` empties the in-process LRU (what a
    fresh replica looks like — tests use it to simulate a cold boot
    against a warm disk tier); `disk=True` deletes the on-disk entries;
    `stats=True` zeroes the counters. Per-wrapper signature memos survive:
    fingerprints are pure functions of the call signature."""
    global _disk_scanned
    if memory:
        with _lock:
            _mem.clear()
    if disk:
        d = _cache_dir()
        if d:
            for path, _mtime, _size in _scan_dir(d):
                try:
                    os.remove(path)
                except OSError:
                    pass
        with _lock:
            _stats["bytes"] = 0
    if stats:
        with _lock:
            for k in _stats:
                _stats[k] = 0
            _disk_scanned = False
            _warned.clear()
