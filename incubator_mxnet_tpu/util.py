"""Misc utilities (reference python/mxnet/util.py, 604 LoC).

The reference's util.py mostly manages numpy-shape/array semantics switches
threaded through the C API; here those are process-local flags consumed by
the mxnet.numpy namespace, plus the small filesystem/env helpers user code
imports.
"""
from __future__ import annotations

import collections
import functools
import os
import threading

__all__ = ["makedirs", "set_np_shape", "is_np_shape", "use_np_shape",
           "np_shape", "set_np_array", "is_np_array", "np_array", "use_np",
           "set_np", "reset_np", "getenv", "setenv", "default_array",
           "ENV_VARS", "EnvSpec", "getenv_int", "getenv_bool", "getenv_str"]

_tls = threading.local()


def makedirs(d):
    """Reference util.py makedirs (py2 compat wrapper there; kept for API)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


# -- numpy-semantics switches (reference util.py set_np_shape:68 etc.) ------

def _flags():
    if not hasattr(_tls, "np_shape"):
        _tls.np_shape = False
        _tls.np_array = False
    return _tls


def set_np_shape(active):
    """Allow zero-dim/zero-size arrays (reference util.py:68). Under jax
    these are always expressible; the flag only controls legacy-shape
    validation in the NDArray layer."""
    prev = _flags().np_shape
    _flags().np_shape = bool(active)
    return prev


def is_np_shape():
    return _flags().np_shape


def set_np_array(active):
    prev = _flags().np_array
    _flags().np_array = bool(active)
    return prev


def is_np_array():
    return _flags().np_array


class _NpShapeScope:
    def __init__(self, shape=True, array=None):
        self._shape = shape
        self._array = array

    def __enter__(self):
        self._prev_shape = set_np_shape(self._shape)
        if self._array is not None:
            self._prev_array = set_np_array(self._array)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev_shape)
        if self._array is not None:
            set_np_array(self._prev_array)


def np_shape(active=True):
    """Context manager (reference util.py np_shape)."""
    return _NpShapeScope(shape=active)


def np_array(active=True):
    return _NpShapeScope(shape=is_np_shape(), array=active)


def use_np_shape(func):
    """Decorator (reference util.py use_np_shape)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)

    return wrapper


def use_np(func):
    """Decorator enabling both np shape + array semantics
    (reference util.py use_np)."""

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with _NpShapeScope(shape=True, array=True):
            return func(*args, **kwargs)

    return wrapper


def set_np(shape=True, array=True):
    set_np_shape(shape)
    set_np_array(array)


def reset_np():
    set_np(False, False)


def getenv(name):
    """Reference util.py getenv -> MXGetEnv."""
    return os.environ.get(name)


def setenv(name, value):
    os.environ[name] = value


# -- environment-variable registry ------------------------------------------
#
# Every MXNET_*/MXTPU_* knob the package reads is declared here once, with
# its type, default, and doc, and read only through getenv_int/getenv_bool/
# getenv_str below.  tools/mxlint enforces this (rules EV01/EV02) and
# tools/diagnose.py prints the table with live values.  The reference
# framework documented its env vars in docs/faq/env_var.md by hand; keeping
# the registry in code makes the doc impossible to forget.

EnvSpec = collections.namedtuple("EnvSpec", ["default", "kind", "doc"])

ENV_VARS = collections.OrderedDict([
    ("MXNET_OPTIMIZER_AGGREGATION_SIZE", EnvSpec(4, "int",
     "Max parameters fused into one multi-tensor optimizer dispatch by "
     "gluon.Trainer; <=1 restores per-tensor updates.")),
    ("MXNET_KVSTORE_BIGARRAY_BOUND", EnvSpec(1000 * 1000, "int",
     "Element count at/above which a kvstore array takes the "
     "ownership-sharded wire (reference kvstore_dist.h bigarray bound).")),
    ("MXNET_KVSTORE_FLATPACK_BOUND", EnvSpec(32 << 20, "int",
     "Flat-pack bucket byte cap for kvstore.pushpull_list gradient "
     "aggregation.")),
    ("MXNET_KVSTORE_BIND_ADDR", EnvSpec("", "str",
     "Interface the dist_async parameter server binds to; empty (default) "
     "binds the coordinator-facing interface only — never 0.0.0.0 unless "
     "set explicitly.")),
    ("MXNET_KVSTORE_ASYNC_ADDR", EnvSpec("", "str",
     "Elastic-join endpoint for the dist_async parameter server as "
     "'host:port token'. When set, a single-process worker connects "
     "directly (no jax.distributed rendezvous) and is assigned a rank by "
     "the server — the replacement-worker path after a kill -9.")),
    ("MXNET_KVSTORE_CONNECT_TIMEOUT", EnvSpec(10, "int",
     "Seconds an AsyncClient waits for one TCP connect + nonce exchange "
     "to the dist_async server before retrying.")),
    ("MXNET_KVSTORE_CALL_TIMEOUT", EnvSpec(60, "int",
     "Seconds an AsyncClient waits for the reply to one RPC frame before "
     "treating the server as wedged and retrying over a fresh "
     "connection.")),
    ("MXNET_KVSTORE_RETRIES", EnvSpec(4, "int",
     "Reconnect/retry attempts (per call and per connect) against a dead "
     "or wedged dist_async server before raising MXNetError.")),
    ("MXNET_KVSTORE_RETRY_BACKOFF_MS", EnvSpec(100, "int",
     "Initial retry backoff in milliseconds; doubles per attempt "
     "(exponential, capped at 10s).")),
    ("MXNET_HEARTBEAT_INTERVAL", EnvSpec(2, "int",
     "Seconds between background worker heartbeats to the dist_async "
     "server's liveness registry.")),
    ("MXNET_DEAD_NODE_TIMEOUT", EnvSpec(30, "int",
     "Seconds without a heartbeat after which the dist_async server "
     "reports a worker dead (get_dead_nodes default; reference "
     "kvstore_dist.h:121 node timeout).")),
    ("MXNET_STRAGGLER_LAG", EnvSpec(100, "int",
     "Heartbeat-reported step lag behind the fastest worker at/above "
     "which a worker is counted a straggler.")),
    ("MXNET_CKPT_QUEUE", EnvSpec(2, "int",
     "Bounded write-behind queue depth of fault.AsyncCheckpointManager; "
     "when full the OLDEST pending snapshot is dropped (newest state "
     "wins) so a slow disk never stalls the train loop.")),
    ("MXNET_FAULT_INJECT", EnvSpec("", "str",
     "Test-suite only: fault-injection spec 'site@n:action[,...]' where "
     "action is kill, drop, or delay=SECONDS — e.g. 'push@5:kill' kills "
     "the process at the 5th kvstore push, 'frame@3:drop' drops the 3rd "
     "wire frame. Empty disables injection.")),
    ("MXNET_COMPILE_WARN_THRESHOLD", EnvSpec(8, "int",
     "Compiles of the same jit key after which the profiler warns about "
     "a likely recompile loop.")),
    ("MXNET_EXEC_CACHE_DIR", EnvSpec("", "str",
     "Persistent executable-cache directory (compile_cache.py): AOT-"
     "compiled XLA executables from the four tracked jit choke points "
     "(op registry, fused optimizer, kvstore flat-pack, serving) are "
     "serialized here and deserialized by later processes, so a fleet "
     "replica cold-starts without recompiling. Empty (default) disables "
     "the disk tier; the in-memory LRU is always on. Distinct from "
     "MXTPU_COMPILE_CACHE (jax's own compilation cache, which still "
     "pays tracing+lowering per process).")),
    ("MXNET_EXEC_CACHE_SIZE", EnvSpec(1024, "int",
     "Entry capacity of the process-wide in-memory executable LRU shared "
     "by all compile_cache.cached_jit call sites; replaces serve's "
     "per-predictor hard executable cap and the per-op FIFO memos as THE "
     "eviction policy.")),
    ("MXNET_EXEC_CACHE_DISK_BYTES", EnvSpec(2 << 30, "int",
     "Byte budget for MXNET_EXEC_CACHE_DIR; after a write pushes "
     "occupancy past it, oldest entries (mtime order) are evicted. "
     "<=0 disables the bound.")),
    ("MXNET_SHARDLINT", EnvSpec(False, "bool",
     "Enable shardlint graph capture: the jit choke points "
     "(compile_cache.cached_jit, profiler.track_jit, tune.tuned_call) and "
     "the partition-rule matcher snapshot jaxprs/coverage reports into "
     "shardlint.captures() for the tools/shardlint rule passes "
     "(SL01-SL05). Off (default), every hook is a cached boolean check on "
     "a once-per-signature path — zero steady-state overhead.")),
    ("MXNET_SHARDLINT_CAPTURES", EnvSpec(256, "int",
     "Bound on the shardlint capture buffer; once full the OLDEST capture "
     "is dropped (counted in shardlint.stats()['dropped']).")),
    ("MXNET_SHARDLINT_CORPUS", EnvSpec("", "str",
     "Comma-separated subset of the tools/shardlint offline model corpus "
     "to trace (see tools.shardlint.corpus.entries()); empty (default) "
     "traces every registered entry.")),
    ("MXNET_HOME", EnvSpec("~/.mxnet", "str",
     "Data directory for downloaded model-zoo parameter files.")),
    ("MXNET_GLUON_REPO", EnvSpec(
     "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/", "str",
     "Base URL for gluon model-zoo downloads.")),
    ("MXTPU_NO_NATIVE", EnvSpec(False, "bool",
     "Disable the C accelerators for recordio/image packing and fall "
     "back to pure python.")),
    ("MXTPU_CONV_BWD_KERNEL", EnvSpec("patch", "str",
     "Conv backward-data kernel choice: 'patch' (default) or 'taps'.")),
    ("MXTPU_FUSED_CONV_BWD", EnvSpec(False, "bool",
     "Enable the experimental fused conv backward pallas kernel.")),
    ("MXNET_TUNE", EnvSpec(True, "bool",
     "Enable the kernel autotuner (tune.py): per-(kernel, shape, dtype, "
     "device) timed selection between hand Pallas kernels and the plain "
     "XLA composition. Off, every tuned_call site runs its XLA "
     "fallback.")),
    ("MXNET_TUNE_SAMPLES", EnvSpec(3, "int",
     "Timed repetitions per autotuner candidate (best-of); the first, "
     "untimed call absorbs compilation.")),
    ("MXTPU_TUNE_INTERPRET", EnvSpec(False, "bool",
     "Offer interpret-mode Pallas candidates to the autotuner off-TPU. "
     "Test-suite only: interpret mode always loses a fair timing race, "
     "so off-TPU candidate sets are empty unless this is set.")),
    ("MXTPU_FUSED_BLOCK", EnvSpec(True, "bool",
     "Route gluon ResNet residual units through the fused "
     "conv+BN(+add)+ReLU ops (autotuned; the XLA candidate keeps the "
     "unfused numerics). Off restores the layer-by-layer oracle path.")),
    ("MXTPU_FP32_MATMUL", EnvSpec("strict", "str",
     "fp32 matmul precision: 'strict' (MXNet semantics, fp32 "
     "accumulate), 'fast' (bf16_3x), or 'fastest' (plain bf16).")),
    ("MXTPU_COMPILE_CACHE", EnvSpec("~/.cache/mxtpu_xla", "str",
     "XLA persistent compilation-cache directory; '0' disables.")),
    ("MXTPU_TEST_PLATFORM", EnvSpec("cpu", "str",
     "Test-suite only: jax platform the suite pins itself to.")),
    ("MXTPU_TEST_SEED", EnvSpec(0, "int",
     "Test-suite only: base RNG seed for the randomized operator tests.")),
    ("MXNET_STEP_ATTRIBUTION", EnvSpec(False, "bool",
     "Enable step-time attribution: profiler.span(phase) wired into "
     "TrainStep.run_epoch / Trainer.step / the serve batcher records "
     "per-phase ms/step (input_wait, h2d, compute, collective, "
     "optimizer, ckpt_snapshot, queue_wait) into dumps(), nested "
     "chrome-trace spans, and mxnet_step_phase_ms histograms. Off (the "
     "default), the span API returns a shared no-op and the hot paths "
     "do zero bookkeeping.")),
    ("MXNET_FLIGHT_RECORDER", EnvSpec("", "str",
     "Directory for the crash flight recorder. When set, fault.py keeps "
     "a bounded ring of recent step records/events and dumps it "
     "atomically as JSON on SIGUSR1, on a FaultInjector trip, and on an "
     "unhandled exception in run_epoch. Empty (the default) disables "
     "the recorder entirely.")),
    ("MXNET_FLIGHT_RECORDER_SIZE", EnvSpec(256, "int",
     "Flight-recorder ring capacity: how many recent step records and "
     "events the postmortem dump retains (oldest dropped first).")),
    ("MXNET_FLEET_OBS", EnvSpec(False, "bool",
     "Enable the fleet observability plane (fleetobs.py): each rank "
     "attaches a bounded metric snapshot (phase histogram deltas, MFU, "
     "exec-cache/tune counters, top compiler cost records) to its "
     "authenticated kvstore heartbeat; the coordinator folds them into a "
     "FleetRegistry serving fleet-wide /metrics, /fleet, and /alerts and "
     "evaluates the SLO burn-rate engine. Off (the default), the "
     "heartbeat payload is byte-identical to the non-fleet wire and no "
     "snapshot work happens.")),
    ("MXNET_FLEET_SNAPSHOT_INTERVAL", EnvSpec(1, "int",
     "Attach a fleet snapshot to every Nth heartbeat (>=1). Raising it "
     "bounds per-beat wire overhead on large fleets; intermediate beats "
     "stay plain v2 heartbeats.")),
    ("MXNET_FLEET_SLO_PATH", EnvSpec("", "str",
     "Path to a fleet SLO spec file (one spec per line, '#' comments; "
     "grammar: 'p99(queue_wait) < 50ms', 'mfu > 0.3', "
     "'straggler_lag < 1.5x'). Empty (the default) loads the built-in "
     "straggler_lag spec only.")),
    ("MXNET_FLEET_SLO_INTERVAL", EnvSpec(5, "int",
     "Seconds between SLO burn-rate evaluations at the coordinator; the "
     "short burn window is one interval, the long window five.")),
    ("MXNET_FLEET_PROFILE_MAX_STEPS", EnvSpec(50, "int",
     "Upper bound on the step count a remote-profile control op may "
     "request from a rank; larger requests are clamped.")),
    ("MXNET_FLEET_PROFILE_MAX_SECONDS", EnvSpec(30, "int",
     "Wall-clock cap on one remote-profile session; the rank stops and "
     "ships whatever it captured when the cap expires before N steps.")),
    ("MXNET_FLEET_PROFILE_MAX_BYTES", EnvSpec(4 << 20, "int",
     "Byte cap on a shipped remote-profile trace segment; oldest events "
     "are dropped until the JSON payload fits, and the coordinator "
     "refuses oversized pushes outright.")),
    ("MXNET_KVSTORE_RETRY_JITTER", EnvSpec(True, "bool",
     "Randomize AsyncClient retry backoff by a uniform [0.5, 1.5) "
     "factor so a fleet of workers does not retry in lockstep after a "
     "coordinator restart (thundering herd). Off restores the "
     "deterministic doubling schedule (tests that assert exact retry "
     "timing).")),
    ("MXNET_ROUTER_DEADLINE_MS", EnvSpec(1000, "int",
     "Default end-to-end deadline for one Router.request, covering "
     "every retry and hedge; a request that cannot complete inside it "
     "fails with a retryable deadline error.")),
    ("MXNET_ROUTER_RETRIES", EnvSpec(3, "int",
     "Retry budget per routed request on RETRYABLE failures only "
     "(connect error, 503 shed); application errors (400/500) are "
     "never retried.")),
    ("MXNET_ROUTER_RETRY_BACKOFF_MS", EnvSpec(10, "int",
     "Initial router retry backoff; doubles per attempt with uniform "
     "[0.5, 1.5) jitter, capped at 1s and always bounded by the "
     "request deadline.")),
    ("MXNET_ROUTER_HEDGE_DELAY_MS", EnvSpec(0, "int",
     "Hedged-request trigger: a second replica is tried when the first "
     "attempt has not answered after this long. 0 (the default) "
     "derives the delay from the router's observed p99 latency "
     "(50ms floor until enough samples exist).")),
    ("MXNET_ROUTER_BREAKER_FAILURES", EnvSpec(5, "int",
     "Consecutive connect/timeout failures that open a replica's "
     "circuit breaker (the replica stops receiving traffic until a "
     "half-open probe succeeds). 503 sheds do NOT count — a shedding "
     "replica is alive.")),
    ("MXNET_ROUTER_BREAKER_COOLDOWN_MS", EnvSpec(2000, "int",
     "How long an open circuit breaker waits before letting one "
     "half-open probe request through; the probe's outcome closes or "
     "re-opens the breaker.")),
    ("MXNET_ROUTER_REFRESH_MS", EnvSpec(500, "int",
     "Router discovery period: how often the replica table is "
     "re-pulled from the coordinator's serve registry.")),
    ("MXNET_ROLLOUT_WAVE_SIZE", EnvSpec(1, "int",
     "Replicas updated per rollout wave; the SLO gate is evaluated "
     "between waves, so smaller waves bound the blast radius of a bad "
     "generation.")),
    ("MXNET_ROLLOUT_SLO_GATE", EnvSpec(True, "bool",
     "Gate rollout waves on the fleet SLO engine: any alert firing "
     "after a wave settles triggers automatic rollback of every "
     "already-updated replica. Off, waves proceed unconditionally.")),
    ("MXNET_ROLLOUT_SETTLE_MS", EnvSpec(200, "int",
     "Post-wave settle time before the SLO gate is consulted, so the "
     "new generation's traffic is actually represented in the "
     "evaluated window.")),
    ("MXNET_SERVE_DRAIN_TIMEOUT", EnvSpec(30, "int",
     "Seconds a draining ModelServer (SIGTERM / rollout weight swap) "
     "waits for in-flight batches to flush before forcing shutdown; "
     "new requests get fast 503 + Retry-After for the duration.")),
    ("MXNET_DECODE_SLOTS", EnvSpec(8, "int",
     "Decode slot-batch width: the ONE fixed shape the continuous-"
     "batching decode executable is compiled for. Sequences are "
     "admitted into and retired from these slots every step; changing "
     "it is a recompile.")),
    ("MXNET_DECODE_QUEUE", EnvSpec(64, "int",
     "Bounded decode admission queue; a stream submitted beyond it is "
     "shed with a retryable Overloaded (503) instead of queueing into "
     "collapse.")),
    ("MXNET_DECODE_MAX_NEW_TOKENS", EnvSpec(32, "int",
     "Default per-stream generation cap when the request does not set "
     "max_new_tokens; also sizes the KV pages claimed at admission.")),
    ("MXNET_DECODE_QUEUE_BOUND_MS", EnvSpec(0, "int",
     "Projected-queue-wait admission bound in ms: shed (503 + "
     "Retry-After) when p95 of recent admission waits scaled by the "
     "current queue depth breaches it — the queue-wait-histogram "
     "admission signal. 0 disables projection shedding (the bounded "
     "queue still sheds).")),
    ("MXNET_KV_PAGE_SIZE", EnvSpec(16, "int",
     "Token rows per KV page. Internal fragmentation is bounded by "
     "page_size-1 rows per sequence; the ragged paged-attention kernel "
     "walks pages of exactly this many rows.")),
    ("MXNET_KV_PAGES", EnvSpec(128, "int",
     "KV page pool capacity shared by all decode slots. Exhaustion "
     "holds the admission queue (retires free pages) and sheds once "
     "the queue itself fills.")),
    ("MXNET_KV_PAGES_PER_SEQ", EnvSpec(8, "int",
     "Per-sequence page-table width (max pages one stream may own). "
     "Requests whose prompt+max_new_tokens exceed it are rejected as "
     "NON-retryable — no replica can serve them.")),
    ("MXNET_PREFIX_CACHE", EnvSpec(True, "bool",
     "Enable the copy-on-write prefix cache on serving engines that "
     "construct one by default (PrefillEngine, disagg-role "
     "ModelServers). A cached prefix is shared read-only by any number "
     "of streams; only the divergent tail page is ever copied.")),
    ("MXNET_PREFIX_CACHE_PAGES", EnvSpec(64, "int",
     "Capacity of the prefix cache in KV pages. Inserts beyond it "
     "evict least-recently-used cached pages, and ONLY pages no live "
     "stream references (allocator refcount down to the cache's own "
     "hold); when nothing is evictable the insert is skipped.")),
    ("MXNET_DISAGG_ROLE", EnvSpec("both", "str",
     "Serving replica role advertised to the ServeRegistry: 'prefill' "
     "(chunked prefill + KV-page export only), 'decode' (token "
     "generation from shipped pages), or 'both' (the PR-13 colocated "
     "engine). The router places prefill traffic on prefill-capable "
     "replicas and decode streams on decode-capable ones.")),
    ("MXNET_DISAGG_PREFILL_CHUNK", EnvSpec(16, "int",
     "Token rows per chunked-prefill step. Long prompts are processed "
     "in fixed chunks of this many positions so a decode-colocated "
     "replica interleaves decode steps between chunks instead of "
     "stalling a whole prompt's worth of prefill; one executable "
     "serves every chunk (start/length are traced scalars).")),
    ("MXNET_DISAGG_SHIP_TTL", EnvSpec(60, "int",
     "Seconds an exported KV-page bundle survives in the "
     "coordinator's page store awaiting pickup by the target decode "
     "replica. Expired bundles are dropped at the next store access; "
     "a consumer arriving late re-runs prefill instead of reading "
     "stale pages.")),
    ("MXTPU_PP_SCHEDULE", EnvSpec("gpipe", "str",
     "Pipeline-parallel microbatch schedule for the composed train "
     "step: 'gpipe' (all-forward then the transposed all-backward), "
     "'1f1b' (one-forward-one-backward steady state with bounded "
     "in-flight activations), 'interleaved' (v virtual chunks per "
     "rank, bubble ~1/v of 1F1B's), or 'zb1' (ZB-H1: backward split "
     "into input-grad and weight-grad half-passes, W-passes filling "
     "the cooldown). An explicit schedule= argument overrides it.")),
    ("MXTPU_PP_VSTAGES", EnvSpec(2, "int",
     "Virtual pipeline chunks per rank (v) for the 'interleaved' "
     "schedule — block params are (v, S)-stacked and rank r runs "
     "virtual stages c*S+r. Ignored by other schedules; an explicit "
     "n_chunks= argument overrides it.")),
    ("MXNET_PP_OFFLOAD", EnvSpec(False, "bool",
     "Offload per-(stage, microbatch) saved activations to pinned "
     "host memory inside the pipelined train step (jax.checkpoint "
     "offload policy on the stage-input residual): per-stage live "
     "HBM is bounded by the in-flight transfer window instead of "
     "the schedule depth, at the price of D2H/H2D traffic the "
     "schedule hides under compute. Composes with MXNET_REMAT none/"
     "full only. Publishes d2h_bytes / offload_wait_ms_per_step "
     "through the profiler counter registry.")),
    ("MXNET_REMAT", EnvSpec("none", "str",
     "Per-stage activation rematerialization policy for pipelined "
     "train steps: 'none' (store), 'dots_saveable' (jax.checkpoint "
     "keeping matmul outputs), or 'full' (recompute everything). "
     "Numerics are bit-identical across policies; only the "
     "memory/recompute trade-off moves.")),
    ("MXNET_SPEC_DECODE", EnvSpec(False, "bool",
     "Enable speculative decoding in DecodeScheduler: a host-side "
     "draft proposes tokens and ONE fixed-shape batched verify "
     "executable scores them per iteration (serve/spec_decode.py). "
     "Greedy outputs are bit-identical to plain decode; this is "
     "purely a throughput knob.")),
    ("MXNET_SPEC_K", EnvSpec(4, "int",
     "Maximum draft tokens proposed per stream per speculative "
     "iteration (the verify executable's width is k+1 and is baked "
     "into its compiled shape). Per-stream depth adapts below this "
     "cap when MXNET_SPEC_ADAPT is on.")),
    ("MXNET_SPEC_ADAPT", EnvSpec(True, "bool",
     "Adapt each stream's draft depth to its measured accept rate: "
     "shrink toward 1 below MXNET_SPEC_ACCEPT_FLOOR_PCT, regrow "
     "toward MXNET_SPEC_K at sustained near-full acceptance. Off: "
     "every stream always proposes MXNET_SPEC_K tokens.")),
    ("MXNET_SPEC_ACCEPT_FLOOR_PCT", EnvSpec(50, "int",
     "Accept-rate floor (percent) for adaptive speculation depth: "
     "below it a stream's k shrinks by one per iteration, bounding "
     "wasted verify work when the draft diverges from the target.")),
    ("MXNET_ROUTER_SLO_SPLIT", EnvSpec(False, "bool",
     "Rank routing candidates by SLO headroom instead of raw load: "
     "prefill placements by TTFT-SLO headroom (MXNET_ROUTER_TTFT_"
     "SLO_MS minus the replica's beaten ttft_p99_ms) and decode "
     "placements by inter-token-SLO headroom, with kv_pages_free as "
     "the tiebreak. Off: dedicated-role-first / most-free-pages "
     "ordering.")),
    ("MXNET_ROUTER_TTFT_SLO_MS", EnvSpec(500, "int",
     "Time-to-first-token SLO target (ms) for the prefill tier's "
     "SLO-split placement ranking.")),
    ("MXNET_ROUTER_TOKEN_SLO_MS", EnvSpec(100, "int",
     "Inter-token latency SLO target (ms) for the decode tier's "
     "SLO-split placement ranking.")),
    ("MXNET_REQTRACE", EnvSpec(False, "bool",
     "Request-scoped tracing across the serving plane "
     "(serve/reqtrace.py): mint a trace context at the router, "
     "propagate it via the X-MXNET-Trace header and the kvstore v2 "
     "wire envelope, and book per-hop chrome-trace spans plus a TTFT "
     "budget breakdown on the /generate done row. Off (default): "
     "zero records, wire frames byte-identical.")),
    ("MXNET_REQTRACE_SAMPLE", EnvSpec(1000, "int",
     "Head-based sampling rate for request tracing, in per-mille "
     "(1000 = trace every request). Unsampled requests still carry "
     "a trace id for tail-exemplar promotion on error/SLO breach, "
     "but emit no spans.")),
    ("MXNET_REQTRACE_RING", EnvSpec(64, "int",
     "Capacity of each request-trace ring (recent sampled requests "
     "and error/SLO-breach exemplars), served at /debugz/requests. "
     "Floored at 4.")),
    ("MXNET_MXSAN", EnvSpec(False, "bool",
     "Witness-based concurrency sanitizer (mxsan.py): lock factories "
     "return instrumented wrappers that record per-thread acquisition "
     "orderings, blocking calls made under a lock, and re-entry on "
     "non-reentrant locks; tools/mxsan cross-checks the observed edges "
     "against tools/mxlint/lock_order.py and reports AB/BA cycles "
     "before they hang. Off (default): factories hand back the raw "
     "stdlib primitives — zero records, zero wrappers.")),
    ("MXNET_MXSAN_RING", EnvSpec(4096, "int",
     "Capacity of the mxsan witness event ring; once full the OLDEST "
     "event is dropped (counted in mxsan.stats()['dropped']). "
     "Floored at 64.")),
    ("MXNET_MXSAN_LOG", EnvSpec("", "str",
     "When set (and MXNET_MXSAN is on), mxsan writes its witness log "
     "(events + observed edge table) to this path as JSON at interpreter "
     "exit, for offline replay via `python -m tools.mxsan <path>`.")),
])

_FALSY = frozenset(("", "0", "false", "off", "no"))


def _spec(name):
    try:
        return ENV_VARS[name]
    except KeyError:
        from .base import MXNetError
        raise MXNetError(
            f"environment variable {name!r} is not declared in "
            f"util.ENV_VARS; add it there with a default and doc")


def getenv_int(name):
    """Declared-default int read of an ENV_VARS entry; an unparseable
    value falls back to the default rather than crashing startup."""
    spec = _spec(name)
    raw = os.environ.get(name)
    if raw is None:
        return spec.default
    try:
        return int(raw)
    except ValueError:
        return spec.default


def getenv_bool(name):
    """Declared-default bool read; '', '0', 'false', 'off', 'no' (any
    case) are False, everything else set is True."""
    spec = _spec(name)
    raw = os.environ.get(name)
    if raw is None:
        return spec.default
    return raw.strip().lower() not in _FALSY


def getenv_str(name):
    """Declared-default string read of an ENV_VARS entry."""
    spec = _spec(name)
    raw = os.environ.get(name)
    return spec.default if raw is None else raw


def default_array(source_array, ctx=None, dtype=None):
    """Array in the currently-active frontend semantics (reference
    util.py default_array)."""
    if is_np_array():
        from . import numpy as np_mod
        return np_mod.array(source_array, dtype=dtype)
    from . import nd
    return nd.array(source_array, dtype=dtype)
