"""Optimizer update operators.

Reference: src/operator/optimizer_op.cc (~4K LoC with -inl.h): optimizer
updates ARE operators (sgd_update, sgd_mom_update, adam_update, ...) so the
engine can fuse/overlap them. Same design here: each update is a registered
jax op — jit-cached, donate-friendly, and usable from both the eager Trainer
path and fully-jitted train steps. Multi-weight fused variants
(multi_sgd_update etc.) take interleaved arg lists like the reference.
"""
from __future__ import annotations

from .registry import register

import jax
import jax.numpy as jnp


@register(name="sgd_update", nondiff=True)
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    return weight - lr * g


@register(name="sgd_mom_update", nondiff=True)
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    mom_new = momentum * mom - lr * g
    return (weight + mom_new, mom_new)


@register(name="nag_mom_update", nondiff=True)
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    mom_new = momentum * mom + g
    return (weight - lr * (g + momentum * mom_new), mom_new)


@register(name="mp_sgd_update", nondiff=True)
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: bf16/fp16 weights with an fp32 master copy
    (reference optimizer_op.cc MP_SGD_Update)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight32
    w32 = weight32 - lr * g
    return (w32.astype(weight.dtype), w32)


@register(name="mp_sgd_mom_update", nondiff=True)
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight32
    mom_new = momentum * mom - lr * g
    w32 = weight32 + mom_new
    return (w32.astype(weight.dtype), mom_new, w32)


@register(name="adam_update", nondiff=True)
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return (weight - lr * m / (jnp.sqrt(v) + epsilon), m, v)


@register(name="ftml_update", nondiff=True)
def ftml_update(weight, grad, d, v, z, *, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1):
    g = grad * rescale_grad
    if clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    g = g + wd * weight
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    return (-z_new / d_new, d_new, v_new, z_new)


@register(name="rmsprop_update", nondiff=True)
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return (w, n_new)


@register(name="rmspropalex_update", nondiff=True)
def rmspropalex_update(weight, grad, n, g_s, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    g_new = (1 - gamma1) * g + gamma1 * g_s
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(n_new - jnp.square(g_new) + epsilon)
    w = weight + delta_new
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return (w, n_new, g_new, delta_new)


@register(name="ftrl_update", nondiff=True)
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(jnp.abs(z_new) > lamda1,
                  -(z_new - jnp.sign(z_new) * lamda1) /
                  ((beta + jnp.sqrt(n_new)) / lr + wd), 0.0)
    return (w.astype(weight.dtype), z_new, n_new)


@register(name="signsgd_update", nondiff=True)
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register(name="signum_update", nondiff=True)
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mom_new = momentum * mom - (1 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return (w, mom_new)


@register(name="adamw_update", nondiff=True)
def adamw_update(weight, grad, mean, var, rescale_grad_arr=None, *, lr, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0, clip_gradient=-1.0,
                 rescale_grad=1.0):
    """Decoupled weight decay Adam (reference src/operator/contrib/adamw.cc).
    The tensor rescale_grad input is the dynamic-loss-scaling hook: when
    it is non-finite (overflowed scale) the reference SKIPS the update,
    leaving weight and state untouched — same contract here."""
    rs = rescale_grad_arr if rescale_grad_arr is not None else rescale_grad
    g = grad * rs
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight)
    ok = jnp.all(jnp.isfinite(jnp.asarray(rs, jnp.float32)))
    return (jnp.where(ok, w, weight), jnp.where(ok, m, mean),
            jnp.where(ok, v, var))


@register(name="multi_sgd_update", nondiff=True)
def multi_sgd_update(*args, lrs, wds, rescale_grad=1.0, clip_gradient=-1.0,
                     num_weights=1):
    """Fused multi-weight SGD (reference optimizer_op.cc multi_sgd_update):
    args = [w0, g0, w1, g1, ...]."""
    outs = []
    for i in range(num_weights):
        w, g = args[2 * i], args[2 * i + 1]
        outs.append(sgd_update.fn(w, g, lr=lrs[i], wd=wds[i],
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="multi_sgd_mom_update", nondiff=True)
def multi_sgd_mom_update(*args, lrs, wds, momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=1):
    outs = []
    for i in range(num_weights):
        w, g, m = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        outs.extend(sgd_mom_update.fn(w, g, m, lr=lrs[i], momentum=momentum,
                                      wd=wds[i], rescale_grad=rescale_grad,
                                      clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="all_finite", nondiff=True)
def all_finite(*arrays, init_output=True):
    """AMP grad-scan (reference src/operator/contrib/all_finite.cc): 1.0 if
    every element of every input is finite."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a.astype(jnp.float32))))
    return ok.astype(jnp.float32)


@register(name="multi_mp_sgd_update", nondiff=True)
def multi_mp_sgd_update(*args, lrs, wds, rescale_grad=1.0, clip_gradient=-1.0,
                        num_weights=1):
    """Fused multi-weight multi-precision SGD (reference optimizer_op.cc
    multi_mp_sgd_update): args = [w0, g0, w32_0, w1, g1, w32_1, ...]."""
    outs = []
    for i in range(num_weights):
        w, g, w32 = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        outs.extend(mp_sgd_update.fn(w, g, w32, lr=lrs[i], wd=wds[i],
                                     rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="multi_mp_sgd_mom_update", nondiff=True)
def multi_mp_sgd_mom_update(*args, lrs, wds, momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, num_weights=1):
    """args = [w0, g0, m0, w32_0, ...] (reference optimizer_op.cc)."""
    outs = []
    for i in range(num_weights):
        w, g, m, w32 = (args[4 * i], args[4 * i + 1], args[4 * i + 2],
                        args[4 * i + 3])
        outs.extend(mp_sgd_mom_update.fn(w, g, m, w32, lr=lrs[i],
                                         momentum=momentum, wd=wds[i],
                                         rescale_grad=rescale_grad,
                                         clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="mp_nag_mom_update", nondiff=True)
def mp_nag_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Multi-precision Nesterov momentum (reference optimizer_op.cc
    mp_nag_mom_update)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight32
    mom_new = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * mom_new)
    return (w32.astype(weight.dtype), mom_new, w32)


@register(name="multi_all_finite", nondiff=True)
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    """Fused finiteness scan over many arrays (reference
    src/operator/contrib/all_finite.cc multi_all_finite)."""
    return all_finite.fn(*arrays, init_output=init_output)


@register(name="mp_adamw_update", nondiff=True)
def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad_arr=None,
                    *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    eta=1.0, clip_gradient=-1.0, rescale_grad=1.0):
    """Multi-precision AdamW (reference src/operator/contrib/adamw.cc
    _mp_adamw_update): fp32 master weights, bf16/fp16 working copy.
    Like adamw_update, a non-finite rescale tensor (loss-scale overflow)
    skips the update instead of poisoning the state with NaN."""
    rs = rescale_grad_arr if rescale_grad_arr is not None else rescale_grad
    g = grad.astype(jnp.float32) * rs
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight32)
    ok = jnp.all(jnp.isfinite(jnp.asarray(rs, jnp.float32)))
    w32 = jnp.where(ok, w32, weight32)
    return (w32.astype(weight.dtype), jnp.where(ok, m, mean),
            jnp.where(ok, v, var), w32)


@register(name="group_adagrad_update",
          aliases=("_contrib_group_adagrad_update",), nondiff=True)
def group_adagrad_update(weight, grad, history, *, lr, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Group AdaGrad: ONE accumulator per row (reference
    src/operator/contrib/optimizer_op-inl.h:46 GroupAdagradParam +
    GroupAdagradDnsRspKernel): h[r] += mean(g[r]^2); w[r] -= lr*g[r] /
    sqrt(h[r]+eps). The reference optimizer allocates its state as
    (rows, 1); accept that shape and hand it back unchanged."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    red = tuple(range(1, g.ndim))
    h_flat = history.reshape(-1)
    h_flat = h_flat + (jnp.mean(jnp.square(g), axis=red) if g.ndim > 1
                       else jnp.square(g))
    scale = lr / jnp.sqrt(h_flat + epsilon)
    return (weight - g * scale.reshape((-1,) + (1,) * (g.ndim - 1)),
            h_flat.reshape(history.shape))


@register(name="_sparse_adagrad_update", aliases=("adagrad_update",),
          nondiff=True)
def sparse_adagrad_update(weight, grad, history, *, lr, epsilon=1e-7,
                          rescale_grad=1.0, clip_gradient=-1.0, wd=0.0):
    """AdaGrad (reference src/operator/optimizer_op-inl.h:2144
    AdagradDnsRspDnsKernel): h += g^2; w -= lr * g / sqrt(h + eps).
    The reference only registers the row_sparse-gradient form; the dense
    form here touches every row, which is identical when the gradient
    covers all rows (and the Optimizer layer handles lazy sparse skips).
    The reference op has NO weight-decay parameter (its AdagradParam
    checks `wd == 0`); accept the keyword for call-site compatibility but
    reject nonzero values the same way."""
    if wd:
        raise ValueError("sparse_adagrad_update: wd must be 0 (the "
                         "reference op rejects nonzero wd; apply decay "
                         "at the Optimizer layer instead)")
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    h = history + jnp.square(g)
    return (weight - lr * g / jnp.sqrt(h + epsilon), h)
