"""Optimizer update operators.

Reference: src/operator/optimizer_op.cc (~4K LoC with -inl.h): optimizer
updates ARE operators (sgd_update, sgd_mom_update, adam_update, ...) so the
engine can fuse/overlap them. Same design here: each update is a registered
jax op — jit-cached, donate-friendly, and usable from both the eager Trainer
path and fully-jitted train steps. Multi-weight fused variants
(multi_sgd_update etc.) take interleaved arg lists like the reference.
"""
from __future__ import annotations

from .registry import get_op, register

import jax
import jax.numpy as jnp


@register(name="sgd_update", nondiff=True)
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
               lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    return weight - lr * g


@register(name="sgd_mom_update", nondiff=True)
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    mom_new = momentum * mom - lr * g
    return (weight + mom_new, mom_new)


@register(name="nag_mom_update", nondiff=True)
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    mom_new = momentum * mom + g
    return (weight - lr * (g + momentum * mom_new), mom_new)


@register(name="mp_sgd_update", nondiff=True)
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: bf16/fp16 weights with an fp32 master copy
    (reference optimizer_op.cc MP_SGD_Update)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight32
    w32 = weight32 - lr * g
    return (w32.astype(weight.dtype), w32)


@register(name="mp_sgd_mom_update", nondiff=True)
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight32
    mom_new = momentum * mom - lr * g
    w32 = weight32 + mom_new
    return (w32.astype(weight.dtype), mom_new, w32)


@register(name="adam_update", nondiff=True)
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return (weight - lr * m / (jnp.sqrt(v) + epsilon), m, v)


@register(name="mp_adam_update", nondiff=True)
def mp_adam_update(weight, grad, mean, var, weight32, *, lr, beta1=0.9,
                   beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    """Multi-precision Adam: bf16/fp16 weights, fp32 master copy + fp32
    moments (reference optimizer_op.cc MP_AdamUpdate pattern)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight32
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - lr * m / (jnp.sqrt(v) + epsilon)
    return (w32.astype(weight.dtype), m, v, w32)


@register(name="ftml_update", nondiff=True)
def ftml_update(weight, grad, d, v, z, *, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1):
    g = grad * rescale_grad
    if clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    g = g + wd * weight
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    return (-z_new / d_new, d_new, v_new, z_new)


@register(name="rmsprop_update", nondiff=True)
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return (w, n_new)


@register(name="rmspropalex_update", nondiff=True)
def rmspropalex_update(weight, grad, n, g_s, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    n_new = (1 - gamma1) * jnp.square(g) + gamma1 * n
    g_new = (1 - gamma1) * g + gamma1 * g_s
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(n_new - jnp.square(g_new) + epsilon)
    w = weight + delta_new
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return (w, n_new, g_new, delta_new)


@register(name="ftrl_update", nondiff=True)
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(jnp.abs(z_new) > lamda1,
                  -(z_new - jnp.sign(z_new) * lamda1) /
                  ((beta + jnp.sqrt(n_new)) / lr + wd), 0.0)
    return (w.astype(weight.dtype), z_new, n_new)


@register(name="signsgd_update", nondiff=True)
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register(name="signum_update", nondiff=True)
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mom_new = momentum * mom - (1 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return (w, mom_new)


@register(name="adamw_update", nondiff=True)
def adamw_update(weight, grad, mean, var, rescale_grad_arr=None, *, lr, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0, clip_gradient=-1.0,
                 rescale_grad=1.0):
    """Decoupled weight decay Adam (reference src/operator/contrib/adamw.cc).
    The tensor rescale_grad input is the dynamic-loss-scaling hook: when
    it is non-finite (overflowed scale) the reference SKIPS the update,
    leaving weight and state untouched — same contract here."""
    rs = rescale_grad_arr if rescale_grad_arr is not None else rescale_grad
    g = grad * rs
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight)
    ok = jnp.all(jnp.isfinite(jnp.asarray(rs, jnp.float32)))
    return (jnp.where(ok, w, weight), jnp.where(ok, m, mean),
            jnp.where(ok, v, var))


@register(name="multi_sgd_update", nondiff=True)
def multi_sgd_update(*args, lrs, wds, rescale_grad=1.0, clip_gradient=-1.0,
                     num_weights=1):
    """Fused multi-weight SGD (reference optimizer_op.cc multi_sgd_update):
    args = [w0, g0, w1, g1, ...]."""
    outs = []
    for i in range(num_weights):
        w, g = args[2 * i], args[2 * i + 1]
        outs.append(sgd_update.fn(w, g, lr=lrs[i], wd=wds[i],
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="multi_sgd_mom_update", nondiff=True)
def multi_sgd_mom_update(*args, lrs, wds, momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=1):
    outs = []
    for i in range(num_weights):
        w, g, m = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        outs.extend(sgd_mom_update.fn(w, g, m, lr=lrs[i], momentum=momentum,
                                      wd=wds[i], rescale_grad=rescale_grad,
                                      clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="all_finite", nondiff=True)
def all_finite(*arrays, init_output=True):
    """AMP grad-scan (reference src/operator/contrib/all_finite.cc): 1.0 if
    every element of every input is finite."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a.astype(jnp.float32))))
    return ok.astype(jnp.float32)


@register(name="multi_mp_sgd_update", nondiff=True)
def multi_mp_sgd_update(*args, lrs, wds, rescale_grad=1.0, clip_gradient=-1.0,
                        num_weights=1):
    """Fused multi-weight multi-precision SGD (reference optimizer_op.cc
    multi_mp_sgd_update): args = [w0, g0, w32_0, w1, g1, w32_1, ...]."""
    outs = []
    for i in range(num_weights):
        w, g, w32 = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        outs.extend(mp_sgd_update.fn(w, g, w32, lr=lrs[i], wd=wds[i],
                                     rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="multi_mp_sgd_mom_update", nondiff=True)
def multi_mp_sgd_mom_update(*args, lrs, wds, momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0, num_weights=1):
    """args = [w0, g0, m0, w32_0, ...] (reference optimizer_op.cc)."""
    outs = []
    for i in range(num_weights):
        w, g, m, w32 = (args[4 * i], args[4 * i + 1], args[4 * i + 2],
                        args[4 * i + 3])
        outs.extend(mp_sgd_mom_update.fn(w, g, m, w32, lr=lrs[i],
                                         momentum=momentum, wd=wds[i],
                                         rescale_grad=rescale_grad,
                                         clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="multi_adam_update", nondiff=True)
def multi_adam_update(*args, lrs, wds, beta1=0.9, beta2=0.999, epsilon=1e-8,
                      rescale_grad=1.0, clip_gradient=-1.0, num_weights=1):
    """Fused multi-weight Adam (reference multi-tensor pattern,
    optimizer_op.cc multi_sgd_* family): args = [w0, g0, m0, v0, w1, ...].
    lrs carry any per-index bias correction already folded in."""
    outs = []
    for i in range(num_weights):
        w, g, m, v = args[4 * i], args[4 * i + 1], args[4 * i + 2], args[4 * i + 3]
        outs.extend(adam_update.fn(w, g, m, v, lr=lrs[i], wd=wds[i],
                                   beta1=beta1, beta2=beta2, epsilon=epsilon,
                                   rescale_grad=rescale_grad,
                                   clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="multi_mp_adam_update", aliases=("multi_mp_adam",),
          nondiff=True)
def multi_mp_adam_update(*args, lrs, wds, beta1=0.9, beta2=0.999,
                         epsilon=1e-8, rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1):
    """args = [w0, g0, m0, v0, w32_0, w1, ...]: fused multi-weight
    multi-precision Adam."""
    outs = []
    for i in range(num_weights):
        w, g, m, v, w32 = (args[5 * i], args[5 * i + 1], args[5 * i + 2],
                           args[5 * i + 3], args[5 * i + 4])
        outs.extend(mp_adam_update.fn(w, g, m, v, w32, lr=lrs[i], wd=wds[i],
                                      beta1=beta1, beta2=beta2,
                                      epsilon=epsilon,
                                      rescale_grad=rescale_grad,
                                      clip_gradient=clip_gradient))
    return tuple(outs)


@register(name="mp_nag_mom_update", nondiff=True)
def mp_nag_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Multi-precision Nesterov momentum (reference optimizer_op.cc
    mp_nag_mom_update)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight32
    mom_new = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * mom_new)
    return (w32.astype(weight.dtype), mom_new, w32)


@register(name="multi_all_finite", nondiff=True)
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    """Fused finiteness scan over many arrays (reference
    src/operator/contrib/all_finite.cc multi_all_finite)."""
    return all_finite.fn(*arrays, init_output=init_output)


@register(name="mp_adamw_update", nondiff=True)
def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad_arr=None,
                    *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    eta=1.0, clip_gradient=-1.0, rescale_grad=1.0):
    """Multi-precision AdamW (reference src/operator/contrib/adamw.cc
    _mp_adamw_update): fp32 master weights, bf16/fp16 working copy.
    Like adamw_update, a non-finite rescale tensor (loss-scale overflow)
    skips the update instead of poisoning the state with NaN."""
    rs = rescale_grad_arr if rescale_grad_arr is not None else rescale_grad
    g = grad.astype(jnp.float32) * rs
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight32)
    ok = jnp.all(jnp.isfinite(jnp.asarray(rs, jnp.float32)))
    w32 = jnp.where(ok, w32, weight32)
    return (w32.astype(weight.dtype), jnp.where(ok, m, mean),
            jnp.where(ok, v, var), w32)


@register(name="group_adagrad_update",
          aliases=("_contrib_group_adagrad_update",), nondiff=True)
def group_adagrad_update(weight, grad, history, *, lr, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Group AdaGrad: ONE accumulator per row (reference
    src/operator/contrib/optimizer_op-inl.h:46 GroupAdagradParam +
    GroupAdagradDnsRspKernel): h[r] += mean(g[r]^2); w[r] -= lr*g[r] /
    sqrt(h[r]+eps). The reference optimizer allocates its state as
    (rows, 1); accept that shape and hand it back unchanged."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    red = tuple(range(1, g.ndim))
    h_flat = history.reshape(-1)
    h_flat = h_flat + (jnp.mean(jnp.square(g), axis=red) if g.ndim > 1
                       else jnp.square(g))
    scale = lr / jnp.sqrt(h_flat + epsilon)
    return (weight - g * scale.reshape((-1,) + (1,) * (g.ndim - 1)),
            h_flat.reshape(history.shape))


@register(name="_sparse_adagrad_update", aliases=("adagrad_update",),
          nondiff=True)
def sparse_adagrad_update(weight, grad, history, *, lr, epsilon=1e-7,
                          rescale_grad=1.0, clip_gradient=-1.0, wd=0.0):
    """AdaGrad (reference src/operator/optimizer_op-inl.h:2144
    AdagradDnsRspDnsKernel): h += g^2; w -= lr * g / sqrt(h + eps).
    The reference only registers the row_sparse-gradient form; the dense
    form here touches every row, which is identical when the gradient
    covers all rows (and the Optimizer layer handles lazy sparse skips).
    The reference op has NO weight-decay parameter (its AdagradParam
    checks `wd == 0`); accept the keyword for call-site compatibility but
    reject nonzero values the same way."""
    if wd:
        raise ValueError("sparse_adagrad_update: wd must be 0 (the "
                         "reference op rejects nonzero wd; apply decay "
                         "at the Optimizer layer instead)")
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    h = history + jnp.square(g)
    return (weight - lr * g / jnp.sqrt(h + epsilon), h)


# ---------------------------------------------------------------------------
# Generic multi-tensor fused dispatch (the engine behind the Trainer's
# aggregated step). Reference: optimizer_op.cc registers hand-written
# multi_* variants and the python layer buckets params up to
# MXNET_OPTIMIZER_AGGREGATION_SIZE; here ONE builder pytree-maps ANY
# registered single-tensor update op over a bucket inside a single jitted
# executable, so every optimizer that names its op gets aggregation for
# free. lr/wd arrive as traced (n,)-vectors — an lr_scheduler step does NOT
# recompile; clip/momentum/betas are static and key the jit cache.
# ---------------------------------------------------------------------------

_fused_cache = {}
_FUSED_CACHE_MAX = 128


def _donation_supported():
    import jax
    return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")


def _fused_fn(op_name, n, arity, static_items, dyn_keys):
    """Build (and cache) the fused executable for a bucket shape-family.

    Call form: f(dyn_vectors_tuple, rescale, *flat) where flat interleaves
    [w0, g0, s0a, ..., w1, g1, ...] (arity arrays per weight). Outputs are
    the interleaved [new_w0, new_s0a, ..., new_w1, ...] — each single op
    returns (weight, *states) in exactly that order. Weight/state buffers
    are donated on backends that support donation (grads are NOT donated:
    the autograd buffers are reused by the next backward)."""
    donate = _donation_supported()
    key = (op_name, n, arity, static_items, dyn_keys, donate)
    f = _fused_cache.get(key)
    if f is not None:
        return f
    op = get_op(op_name)
    static = dict(static_items)

    def fused(dyn, rescale, *flat):
        outs = []
        for i in range(n):
            args = flat[arity * i:arity * (i + 1)]
            kw = {k: dyn[j][i] for j, k in enumerate(dyn_keys)}
            res = op.fn(*args, rescale_grad=rescale, **kw, **static)
            outs.extend(res if isinstance(res, tuple) else (res,))
        return tuple(outs)

    # two-tier executable cache (donation is part of the jit options the
    # fingerprint covers): reports hit/disk-hit/retrace telemetry and lets
    # a fresh trainer process deserialize the fused step instead of
    # recompiling it
    from .. import compile_cache as _cc
    from .. import shardlint as _sl
    # role map for shardlint's donation audit (SL03): args 0/1 are the
    # dyn-vector tuple and rescale scalar; within each weight's
    # arity-slot, position 1 is the gradient, the rest are weight/state
    _sl.annotate(f"fused:{op_name}[n={n}]",
                 arg_roles={2 + j: ("grads" if j % arity == 1 else "params")
                            for j in range(arity * n)})
    if donate:
        # flat starts at position 2; within each weight's arity-slot,
        # position 1 is the gradient — everything else is donatable
        argnums = tuple(2 + j for j in range(arity * n) if j % arity != 1)
        f = _cc.cached_jit(f"fused:{op_name}[n={n}]", fused,
                           donate_argnums=argnums)
    else:
        f = _cc.cached_jit(f"fused:{op_name}[n={n}]", fused)
    if len(_fused_cache) >= _FUSED_CACHE_MAX:
        _fused_cache.pop(next(iter(_fused_cache)))
    _fused_cache[key] = f
    return f


def _probe_bucket(optimizer, indices, weights, grads, states):
    """Dry-run the bucket WITHOUT touching optimizer step counters: every
    param must map to the same (op, static-kwargs, dyn-keys) and carry a
    dense gradient. Returns the common (op_name, static_items) or None —
    the caller falls back to the per-param oracle."""
    from ..ndarray.ndarray import NDArray

    common = None
    for i, w, g, s in zip(indices, weights, grads, states):
        if not isinstance(w, NDArray) or not isinstance(g, NDArray):
            return None
        if getattr(g, "stype", "default") != "default":
            return None
        if str(w.dtype) != str(weights[0].dtype):
            return None
        spec = optimizer._fused_spec(i, w, s)
        if spec is None:
            return None
        op_name, static = spec[0], tuple(sorted(spec[2].items()))
        if common is None:
            common = (op_name, static)
        elif common != (op_name, static):
            return None
    return common


def fused_apply(optimizer, indices, weights, grads, states):
    """Apply `optimizer` to a whole bucket in ONE jitted dispatch.

    Commits the per-index update counts only once the bucket is known to be
    fusable, then gathers the step's dynamic hyperparams (lr with any bias
    correction folded in, wd) into traced vectors and runs the cached fused
    executable. Returns True when the fused path ran; False means nothing
    happened and the caller must run the per-param oracle."""
    common = _probe_bucket(optimizer, indices, weights, grads, states)
    if common is None:
        return False
    op_name, static_items = common
    for i in indices:
        optimizer._update_count(i)

    n = len(indices)
    dyn_rows = []               # one {key: value} per param, post-count
    state_rows = []             # ordered extra-array operands per param
    for i, w, g, s in zip(indices, weights, grads, states):
        _, st_arrs, _, dyn = optimizer._fused_spec(i, w, s)
        state_rows.append(st_arrs)
        dyn_rows.append(dyn)
    dyn_keys = tuple(sorted(dyn_rows[0]))
    arity = 2 + len(state_rows[0])
    # mp ops compute on the fp32 master copy — their hyperparams are fp32;
    # plain ops follow the weight dtype (weak-typing parity with the
    # python-float constants the per-param oracle bakes in)
    hdt = jnp.float32 if op_name.startswith("mp_") else weights[0]._data.dtype
    dyn_vecs = tuple(jnp.asarray([row[k] for row in dyn_rows], dtype=hdt)
                     for k in dyn_keys)
    rescale = jnp.asarray(optimizer.rescale_grad, dtype=hdt)
    flat = []
    for w, g, st_arrs in zip(weights, grads, state_rows):
        flat.append(w._data)
        flat.append(g._data)
        flat.extend(a._data for a in st_arrs)

    f = _fused_fn(op_name, n, arity, static_items, dyn_keys)
    from . import registry as _registry
    if _registry.PROFILER_HOOK is not None:
        out = _registry.PROFILER_HOOK(f"multi:{op_name}[{n}]", f,
                                      (dyn_vecs, rescale) + tuple(flat))
    else:
        out = f(dyn_vecs, rescale, *flat)

    per = arity - 1             # outputs per weight: new_w + new states
    for j, (w, st_arrs) in enumerate(zip(weights, state_rows)):
        w._data = out[per * j]
        for k, a in enumerate(st_arrs):
            a._data = out[per * j + 1 + k]
    from .. import profiler as _prof
    if _prof.memory_enabled():
        # donation path swaps raw jax buffers into live NDArrays without
        # constructing wrappers — account the fresh buffers explicitly
        # (donated inputs decrement through their finalizers on release)
        for o in out:
            _prof.memory_event(o, tag=f"fused_apply:{op_name}")
    return True
