"""Fused RNN operator: relu/tanh RNN, LSTM, GRU; multi-layer, bidirectional.

Reference: src/operator/rnn-inl.h (modes at :64-70, cuDNN path :704+, native
CPU rnn_impl.h). The reference packs all parameters into ONE flat vector in
cuDNN layout — weights for every (layer, direction) first, then biases —
and mutates per-timestep workspaces.

TPU-native redesign: the sequence loop is `lax.scan` over time with the
input-to-hidden projection hoisted OUT of the scan (one big [T*N, in]x[in,
G*H] matmul that rides the MXU; the scan body only does the [N,H]x[H,G*H]
recurrent matmul). Gate order and equations match cuDNN exactly so flat
parameter vectors from reference checkpoints drop in:
  LSTM gates [i, f, g, o]; GRU gates [r, z, n] with the reset gate applied
  AFTER the hidden projection (cuDNN's linear_before_reset semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

__all__ = ["rnn_forward", "GATES"]

GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _cell_step(mode, x_proj, h, c, w_hh, b_hh, clip=None):
    """One timestep. x_proj already includes W_ih x + b_ih."""
    if mode == "gru":
        # cuDNN: r/z from the summed projections; n uses r *after* the
        # hidden-side linear (linear_before_reset)
        xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
        hproj = h @ w_hh.T + b_hh
        hr, hz, hn = jnp.split(hproj, 3, axis=-1)
        rg = jax.nn.sigmoid(xr + hr)
        zg = jax.nn.sigmoid(xz + hz)
        ng = jnp.tanh(xn + rg * hn)
        return (1 - zg) * ng + zg * h, c
    r = x_proj + h @ w_hh.T + b_hh
    if mode == "rnn_relu":
        return jnp.maximum(r, 0), c
    if mode == "rnn_tanh":
        return jnp.tanh(r), c
    if mode == "lstm":
        i, f, g, o = jnp.split(r, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        if clip is not None:
            lo, hi, clip_nan = clip
            if clip_nan:
                c_new = jnp.nan_to_num(c_new, nan=0.0)
            c_new = jnp.clip(c_new, lo, hi)
        return o * jnp.tanh(c_new), c_new
    raise MXNetError(f"unknown RNN mode {mode!r}")


def _scan_layer(mode, xs, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse=False,
                clip=None):
    """Run one direction of one layer over the whole sequence.

    xs: [T, N, in]; returns (out [T, N, H], h_T, c_T)."""
    T, N = xs.shape[0], xs.shape[1]
    # hoist the input projection out of the scan: one MXU-sized matmul
    # explicit sizes, not -1: inference divides by T*N, breaking N=0 batches
    x_proj = (xs.reshape(T * N, xs.shape[2]) @ w_ih.T
              + b_ih).reshape(T, N, w_ih.shape[0])
    if reverse:
        x_proj = jnp.flip(x_proj, axis=0)

    def step(carry, xp):
        h, c = carry
        h_new, c_new = _cell_step(mode, xp, h, c, w_hh, b_hh, clip=clip)
        return (h_new, c_new), h_new

    (h_T, c_T), out = lax.scan(step, (h0, c0), x_proj)
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, h_T, c_T


def rnn_forward(xs, h0, c0, layer_params, mode, bidirectional=False,
                dropout=0.0, training=False, rng=None, clip=None):
    """Functional multi-layer (bi)RNN.

    xs: [T, N, input]; h0/c0: [L*D, N, H];
    layer_params: list over (layer, direction) of (w_ih, w_hh, b_ih, b_hh).
    Returns (out [T, N, H*D], h_T [L*D, N, H], c_T [L*D, N, H]).
    """
    D = 2 if bidirectional else 1
    L = len(layer_params) // D
    hs, cs = [], []
    cur = xs
    key = rng
    for layer in range(L):
        outs = []
        for d in range(D):
            idx = layer * D + d
            w_ih, w_hh, b_ih, b_hh = layer_params[idx]
            out, h_T, c_T = _scan_layer(mode, cur, h0[idx], c0[idx],
                                        w_ih, w_hh, b_ih, b_hh,
                                        reverse=(d == 1), clip=clip)
            outs.append(out)
            hs.append(h_T)
            cs.append(c_T)
        cur = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if dropout and training and layer < L - 1:
            if key is None:
                raise MXNetError("RNN dropout requires an rng key")
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1 - dropout, cur.shape)
            cur = jnp.where(keep, cur / (1 - dropout), 0).astype(cur.dtype)
    return cur, jnp.stack(hs), jnp.stack(cs)


def _unpack_flat_params(parameters, mode, input_size, state_size, num_layers,
                        bidirectional):
    """Slice the cuDNN-layout flat vector (reference rnn-inl.h
    GetRnnParamSize: all weights first, then all biases)."""
    G = GATES[mode]
    D = 2 if bidirectional else 1
    H = state_size
    shapes_w = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * D
        for _ in range(D):
            shapes_w.append((G * H, in_sz))
            shapes_w.append((G * H, H))
    off = 0
    weights = []
    for shp in shapes_w:
        n = shp[0] * shp[1]
        weights.append(parameters[off:off + n].reshape(shp))
        off += n
    biases = []
    for _ in range(num_layers * D * 2):
        biases.append(parameters[off:off + G * H])
        off += G * H
    layer_params = []
    for i in range(num_layers * D):
        layer_params.append((weights[2 * i], weights[2 * i + 1],
                             biases[2 * i], biases[2 * i + 1]))
    return layer_params


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional):
    G = GATES[mode]
    D = 2 if bidirectional else 1
    H = state_size
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * D
        size += D * (G * H * in_sz + G * H * H + 2 * G * H)
    return size


@register(name="RNN", aliases=("rnn",), stateful=True, train_aware=True)
def rnn_op(data, parameters, state, state_cell=None, *, state_size,
           num_layers, mode="lstm", bidirectional=False, p=0.0,
           state_outputs=False, projection_size=None, use_sequence_length=False,
           lstm_state_clip_min=None, lstm_state_clip_max=None,
           lstm_state_clip_nan=False, training=False, rng=None):
    """Fused RNN (reference src/operator/rnn-inl.h RNNParam).

    data: [T, N, input] (TNC). parameters: flat vector in cuDNN layout.
    state: [L*D, N, H]; state_cell: LSTM cell state.
    Returns out, or (out, state_h[, state_cell]) when state_outputs.
    """
    if projection_size is not None:
        raise MXNetError("projection_size is not supported")
    if use_sequence_length:
        raise MXNetError(
            "use_sequence_length is not supported by the fused RNN op; "
            "mask with SequenceMask/SequenceLast or use cell unroll with "
            "valid_length")
    layer_params = _unpack_flat_params(parameters, mode, data.shape[2],
                                       state_size, num_layers, bidirectional)
    c0 = state_cell if state_cell is not None else jnp.zeros_like(state)
    # cuDNN clips the cell state at EVERY timestep (rnn-inl.h
    # lstm_state_clip_*), so the clip threads into the scan body
    clip = None
    if mode == "lstm" and lstm_state_clip_min is not None \
            and lstm_state_clip_max is not None:
        clip = (lstm_state_clip_min, lstm_state_clip_max,
                bool(lstm_state_clip_nan))
    out, h_T, c_T = rnn_forward(data, state, c0, layer_params, mode,
                                bidirectional=bidirectional, dropout=p,
                                training=training, rng=rng, clip=clip)
    if not state_outputs:
        return out
    if mode == "lstm":
        return (out, h_T, c_T)
    return (out, h_T)
