"""Neural-network operators: conv, pooling, dense, norms, activations, dropout.

Reference: src/operator/nn/ (28,295 LoC — Convolution/FullyConnected/BatchNorm/
Pooling/Softmax/Activation/Dropout/LayerNorm/... plus cuDNN/MKL-DNN wrapper
trees). TPU-native redesign: every op is a single XLA-lowerable jax function —
convolution is `lax.conv_general_dilated` (XLA tiles it onto the MXU directly;
there is no im2col/cudnn-algo-select analog), pooling is `lax.reduce_window`,
and normalization/activation ops are elementwise chains XLA fuses into
neighboring matmuls, which is the TPU replacement for the reference's
hand-fused cuDNN kernels.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, dtype_np
from .. import tune
from .registry import register

import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------------------------------------
# FullyConnected (reference src/operator/nn/fully_connected.cc:245-333)
# --------------------------------------------------------------------------

@register(name="FullyConnected", aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, *, num_hidden=0, no_bias=False,
                    flatten=True):
    x = data
    if flatten and x.ndim > 2:
        x = jnp.reshape(x, (x.shape[0], -1))
    elif not flatten and x.ndim > 2:
        pass  # apply to last axis
    out = jnp.matmul(x, weight.T) if x.ndim <= 2 else jnp.einsum("...i,oi->...o", x, weight)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# --------------------------------------------------------------------------
# Convolution / Deconvolution (reference src/operator/nn/convolution.cc,
# deconvolution.cc; im2col.cuh / depthwise_convolution_tf.cuh have no analog —
# XLA handles layout + MXU tiling)
# --------------------------------------------------------------------------

def _conv_dnums(nd_):
    # MXNet layouts are channel-first: NCW / NCHW / NCDHW.
    spatial = "WHD"[:nd_][::-1] if nd_ > 1 else "W"
    spatial = {1: "W", 2: "HW", 3: "DHW"}[nd_]
    return lax.conv_dimension_numbers(
        (1, 1) + (1,) * nd_, (1, 1) + (1,) * nd_,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))


def _tup(v, n, default):
    if v is None or (hasattr(v, "__len__") and len(v) == 0):
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _stem_s2d_parts(data, weight, k):
    """The space-to-depth input/weight transforms plus the equivalent
    stride-1 conv geometry (m, pad lo/hi), shared by _stem_s2d_conv and
    the fused conv+BN+ReLU inference path."""
    n, c, h, w = data.shape
    x = data.reshape(n, c, h // 2, 2, w // 2, 2)
    x = x.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * 4, h // 2, w // 2)
    o = weight.shape[0]
    m = (k + 1) // 2
    wp = jnp.pad(weight, ((0, 0), (0, 0), (1, 0), (1, 0)))
    wp = wp.reshape(o, c, m, 2, m, 2)
    wp = wp.transpose(0, 1, 3, 5, 2, 4).reshape(o, c * 4, m, m)
    lo = (k // 2 + 1) // 2
    hi = (k - k // 2 - 2) // 2
    return x, wp, m, lo, hi


def _stem_s2d_conv(data, weight, k):
    """Space-to-depth rewrite of a k x k stride-2 'same' conv on a skinny
    channel input (the ResNet/Inception stem shape): 2x2 space-to-depth on
    the input, the kernel zero-padded to (k+1) and folded the same way,
    then an m x m STRIDE-1 conv (m = (k+1)/2) on 4x the channels.

    Mathematically identical (the MLPerf conv0 space-to-depth trick); on
    TPU it replaces a C_in=3 conv — which wastes 125/128 of every MXU pass
    — with a C_in=12 stride-1 conv XLA tiles far better. Exact only for
    k % 4 == 3 (pad k//2 odd), stride 2, dilation 1, groups 1, even H/W.
    """
    x, wp, _, lo, hi = _stem_s2d_parts(data, weight, k)
    dn = _conv_dnums(2)
    return lax.conv_general_dilated(
        x, wp, window_strides=(1, 1), padding=[(lo, hi), (lo, hi)],
        dimension_numbers=dn,
        preferred_element_type=jnp.float32 if data.dtype == jnp.float32
        else None)


def _stem_eligible(data, kernel, stride, dilate, pad, num_group):
    """The _stem_s2d_conv exactness conditions (see its docstring)."""
    return (len(kernel) == 2 and num_group == 1 and stride == (2, 2)
            and dilate == (1, 1) and kernel[0] == kernel[1]
            and kernel[0] % 4 == 3 and pad == (kernel[0] // 2,) * 2
            and data.ndim == 4 and data.shape[1] <= 8
            and data.shape[2] % 2 == 0 and data.shape[3] % 2 == 0
            and jax.default_backend() == "tpu")


def _conv_xla(data, weight, kernel, stride, dilate, pad, num_group):
    nd_ = len(kernel)
    dn = _conv_dnums(nd_)
    return lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        lhs_dilation=(1,) * nd_,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.float32 if data.dtype == jnp.float32
        else None)


def _conv3x3_xla(data, weight):
    """The plain-XLA candidate the tuned 3x3 table races against."""
    return _conv_xla(data, weight, (3, 3), (1, 1), (1, 1), (1, 1), 1)


def _conv_core(data, weight, kernel, stride, dilate, pad, num_group):
    """Convolution dispatch shared by the Convolution op and the fused
    conv+BN+ReLU paths: stem space-to-depth rewrite, then the tuned 3x3
    table (parallel/conv_backward's fused-backward kernel raced against
    XLA's native vjp — selection by measurement, never by heuristic),
    then plain XLA."""
    kernel = tuple(int(x) for x in kernel)
    if _stem_eligible(data, kernel, stride, dilate, pad, num_group):
        return _stem_s2d_conv(data, weight, kernel[0])
    if (kernel == (3, 3) and stride == (1, 1) and dilate == (1, 1)
            and pad == (1, 1) and num_group == 1 and data.ndim == 4):
        from ..parallel import conv_backward  # noqa: F401 — registers conv3x3
        return tune.tuned_call("conv3x3", _conv3x3_xla, data, weight)
    return _conv_xla(data, weight, kernel, stride, dilate, pad, num_group)


@register(name="Convolution", aliases=("convolution", "Convolution_v1"))
def convolution(data, weight, bias=None, *, kernel, stride=(), dilate=(), pad=(),
                num_filter=0, num_group=1, workspace=1024, no_bias=False,
                cudnn_tune=None, cudnn_off=False, layout=None):
    nd_ = len(kernel)
    stride = _tup(stride, nd_, 1)
    dilate = _tup(dilate, nd_, 1)
    pad = _tup(pad, nd_, 0)
    out = _conv_core(data, weight, kernel, stride, dilate, pad, num_group)
    if bias is not None and not no_bias:
        out = out + jnp.reshape(bias, (1, -1) + (1,) * nd_)
    return out.astype(data.dtype)


@register(name="Deconvolution", aliases=("deconvolution",))
def deconvolution(data, weight, bias=None, *, kernel, stride=(), dilate=(), pad=(),
                  adj=(), target_shape=(), num_filter=0, num_group=1,
                  workspace=512, no_bias=True, cudnn_tune=None, cudnn_off=False,
                  layout=None):
    """Transposed convolution = gradient of Convolution w.r.t. its input
    (reference src/operator/nn/deconvolution-inl.h)."""
    nd_ = len(kernel)
    stride = _tup(stride, nd_, 1)
    dilate = _tup(dilate, nd_, 1)
    pad = _tup(pad, nd_, 0)
    adj = _tup(adj, nd_, 0)
    dn = _conv_dnums(nd_)
    # weight layout for deconv in MXNet: (C_in, C_out/group, *kernel)
    out = lax.conv_general_dilated(
        data, jnp.flip(jnp.swapaxes(weight, 0, 1), axis=tuple(range(2, 2 + nd_))),
        window_strides=(1,) * nd_,
        padding=[(dilate[i] * (kernel[i] - 1) - pad[i],
                  dilate[i] * (kernel[i] - 1) - pad[i] + adj[i]) for i in range(nd_)],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + jnp.reshape(bias, (1, -1) + (1,) * nd_)
    return out.astype(data.dtype)


# --------------------------------------------------------------------------
# Pooling (reference src/operator/nn/pooling.cc, pool.h/pool.cuh)
# --------------------------------------------------------------------------

@register(name="Pooling", aliases=("pooling", "Pooling_v1"))
def pooling(data, *, kernel=(), pool_type="max", global_pool=False, stride=(),
            pad=(), pooling_convention="valid", count_include_pad=True,
            cudnn_off=False, p_value=2, layout=None):
    nd_ = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd_
        pad = (0,) * nd_
    else:
        kernel = _tup(kernel, nd_, 1)
        stride = _tup(stride, nd_, 1)
        pad = _tup(pad, nd_, 0)

    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    if pooling_convention == "full" and not global_pool:
        # ceil output size (reference pooling-inl.h kFull): widen right pad.
        extra = []
        for i in range(nd_):
            insz = data.shape[2 + i] + 2 * pad[i]
            rem = (insz - kernel[i]) % stride[i]
            extra.append((stride[i] - rem) % stride[i] if rem else 0)
        pads = [(0, 0), (0, 0)] + [(pad[i], pad[i] + extra[i]) for i in range(nd_)]
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]

    # NOTE: init values must be weak-typed python scalars — jax's
    # reduce_window autodiff rule does not linearize with array inits.
    if pool_type == "max":
        # int pools (the quantized path) need a dtype-exact init scalar;
        # float pools keep the weak python scalar (see NOTE above)
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else _np.dtype(data.dtype).type(jnp.iinfo(data.dtype).min)
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0., lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return (s / denom).astype(data.dtype)
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0., lax.add, window, strides, pads)
        return (s / cnt).astype(data.dtype)
    if pool_type == "lp":
        pw = lax.reduce_window(jnp.abs(data) ** p_value, 0., lax.add,
                               window, strides, pads)
        return (pw ** (1.0 / p_value)).astype(data.dtype)
    raise MXNetError(f"unknown pool_type {pool_type}")


# --------------------------------------------------------------------------
# Normalization (reference src/operator/nn/batch_norm.cc, layer_norm.cc,
# group_norm.cc, instance_norm.cc, lrn.cc)
# --------------------------------------------------------------------------

def _bn_batch_stats(data, red):
    """Batch (mean, var) in f32 over reduce axes ``red``.

    ONE pass over the full activation for both statistics: sibling
    sum/sum-of-squares reductions multi-output-fuse in XLA, where
    mean-then-var reads the (large) activation from HBM twice. f32
    accumulation regardless of input dtype (bf16 sums would lose
    mass at ResNet-scale reduction counts). The reductions run on
    data SHIFTED by a per-channel estimate taken from ONE slice of
    the reduce dims (a 1/N-cost pre-read): var is shift-invariant,
    and a shift within O(std) of the true mean kills the
    E[x^2]-E[x]^2 catastrophic cancellation for badly-centered
    activations (|mean| >> std) — unconditionally, unlike a
    moving_mean shift, which is garbage at cold start.
    """
    n = 1
    for i in red:
        n *= data.shape[i]
    if n == 0:
        # 0-size batch: the shifted one-pass path below slices [0:1]
        # of an empty reduce axis (a TypeError); the plain reductions
        # keep the old NaN-stats-no-crash contract for this edge
        return (jnp.mean(data.astype(jnp.float32), axis=red),
                jnp.var(data.astype(jnp.float32), axis=red))
    first = lax.slice_in_dim(data, 0, 1, axis=red[0])
    c = jnp.mean(first.astype(jnp.float32), axis=red, keepdims=True)
    shifted = data.astype(jnp.float32) - c
    s1 = jnp.sum(shifted, axis=red, dtype=jnp.float32)
    s2 = jnp.sum(jnp.square(shifted), axis=red, dtype=jnp.float32)
    dmean = s1 / n
    mean = jnp.reshape(c, (-1,)) + dmean
    var = jnp.maximum(s2 / n - jnp.square(dmean), 0.0)
    return mean, var


def _bn_scale_bias(gamma, beta, mean, var, eps, fix_gamma):
    """BN recomposed as one multiply-add epilogue (scale/bias are C-sized
    — the per-channel math costs nothing; the activation is touched
    once)."""
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    scale = g * jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    bias = beta - mean * scale
    return scale, bias


def _bn_apply_xla(data, scale, bias):
    """Plain-XLA candidate for the tuned BN apply epilogue."""
    from ..parallel.fused_conv import bn_act_reference
    return bn_act_reference(data, scale, bias, relu=False)


def _bn_act_xla(data, scale, bias):
    """Plain-XLA candidate for the tuned BN+ReLU epilogue."""
    from ..parallel.fused_conv import bn_act_reference
    return bn_act_reference(data, scale, bias, relu=True)


def _bn_add_act_xla(data, scale, bias, residual):
    """Plain-XLA candidate for the tuned BN+residual-add+ReLU epilogue."""
    from ..parallel.fused_conv import bn_act_reference
    return bn_act_reference(data, scale, bias, residual, relu=True)


def _conv_bn_relu_xla(data, weight, scale, bias, *, k, pad_lo, pad_hi):
    """Plain-XLA candidate for the tuned fused conv+BN+ReLU forward."""
    from ..parallel.fused_conv import conv_bn_relu_reference
    return conv_bn_relu_reference(data, weight, scale, bias, k, pad_lo,
                                  pad_hi)


def _bn_apply(data, scale, bias, ax):
    """The BN scale/bias apply, autotuned on the NCHW fast path."""
    if ax == 1 and data.ndim == 4:
        from ..parallel import fused_conv  # noqa: F401 — registers epilogues
        return tune.tuned_call("bn_apply", _bn_apply_xla, data, scale, bias)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    return (data * jnp.reshape(scale, shape)
            + jnp.reshape(bias, shape)).astype(data.dtype)


@register(name="BatchNorm", aliases=("batch_norm", "BatchNorm_v1"), train_aware=True)
def batch_norm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, training=False):
    """Returns (out, batch_mean, batch_var); the Gluon layer owns the running-
    stat update (the reference op mutates moving_mean in-place inside the
    kernel — src/operator/nn/batch_norm.cc:417; functional here for XLA)."""
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    if training and not use_global_stats:
        mean, var = _bn_batch_stats(data, red)
        mean = mean.astype(moving_mean.dtype)
        var = var.astype(moving_var.dtype)
    else:
        mean, var = moving_mean, moving_var
    scale, bias = _bn_scale_bias(gamma, beta, mean, var, eps, fix_gamma)
    return (_bn_apply(data, scale, bias, ax), mean, var)


@register(name="FusedBNAddReLU", aliases=("fused_bn_add_relu",),
          train_aware=True)
def fused_bn_add_relu(data, gamma, beta, moving_mean, moving_var,
                      residual=None, *, eps=1e-3, momentum=0.9,
                      fix_gamma=True, use_global_stats=False, axis=1,
                      training=False):
    """BatchNorm + optional residual add + ReLU as ONE op, with the apply
    chain dispatched through the autotuned epilogue table (reference: the
    fused NHWC bn-add-relu kernels under src/operator/nn/batch_norm.cu).
    Same contract as BatchNorm — returns (out, batch_mean, batch_var) and
    the Gluon block owns the running-stat update. Numerics match the
    layer-by-layer composition exactly: the BN output is rounded to the
    data dtype BEFORE the residual add and ReLU."""
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    if training and not use_global_stats:
        mean, var = _bn_batch_stats(data, red)
        mean = mean.astype(moving_mean.dtype)
        var = var.astype(moving_var.dtype)
    else:
        mean, var = moving_mean, moving_var
    scale, bias = _bn_scale_bias(gamma, beta, mean, var, eps, fix_gamma)
    if ax == 1 and data.ndim == 4:
        from ..parallel import fused_conv  # noqa: F401 — registers epilogues
        if residual is None:
            out = tune.tuned_call("bn_act", _bn_act_xla, data, scale, bias)
        else:
            out = tune.tuned_call("bn_add_act", _bn_add_act_xla, data,
                                  scale, bias, residual)
    else:
        out = _bn_apply(data, scale, bias, ax)
        if residual is not None:
            out = out + residual
        out = jnp.maximum(out, 0)
    return (out, mean, var)


def _conv_bn_relu_infer(data, weight, scale, bias, kernel, stride, dilate,
                        pad, num_group, residual):
    """Inference fused-forward dispatch: the moving stats are already
    folded into scale/bias, so the whole chain is ONE tuned kernel when
    the conv is stride-1 same-size (directly, or via the stem
    space-to-depth rewrite); anything else is conv + tuned epilogue."""
    k = kernel[0] if kernel else 0
    if residual is None and _stem_eligible(data, kernel, stride, dilate,
                                           pad, num_group):
        x2, w2, m, lo, hi = _stem_s2d_parts(data, weight, k)
        return tune.tuned_call("conv_bn_relu", _conv_bn_relu_xla, x2, w2,
                               scale, bias, k=m, pad_lo=(lo, lo),
                               pad_hi=(hi, hi))
    if (residual is None and len(kernel) == 2 and kernel == (k, k)
            and k % 2 == 1 and stride == (1, 1) and dilate == (1, 1)
            and pad == (k // 2,) * 2 and num_group == 1 and data.ndim == 4):
        return tune.tuned_call("conv_bn_relu", _conv_bn_relu_xla, data,
                               weight, scale, bias, k=k,
                               pad_lo=(k // 2,) * 2, pad_hi=(k // 2,) * 2)
    z = _conv_core(data, weight, kernel, stride, dilate, pad,
                   num_group).astype(data.dtype)
    if residual is None:
        return tune.tuned_call("bn_act", _bn_act_xla, z, scale, bias)
    return tune.tuned_call("bn_add_act", _bn_add_act_xla, z, scale, bias,
                           residual)


@register(name="FusedConvBNReLU", aliases=("fused_conv_bn_relu",),
          train_aware=True)
def fused_conv_bn_relu(data, weight, gamma, beta, moving_mean, moving_var,
                       residual=None, *, kernel, stride=(), dilate=(),
                       pad=(), num_filter=0, num_group=1, eps=1e-3,
                       momentum=0.9, fix_gamma=True, use_global_stats=False,
                       training=False):
    """Convolution + BatchNorm + (optional residual add) + ReLU as one op
    (reference: cudnnConvolutionBiasActivationForward in
    src/operator/nn/cudnn/). Inference folds the moving stats into a
    per-channel scale/bias and dispatches the autotuned fused forward
    kernel; training must materialize the conv output for the batch
    statistics, so it fuses the epilogue only. Returns (out, mean, var)
    with BatchNorm's contract."""
    nd_ = len(kernel)
    kernel = tuple(int(x) for x in kernel)
    stride = _tup(stride, nd_, 1)
    dilate = _tup(dilate, nd_, 1)
    pad = _tup(pad, nd_, 0)
    from ..parallel import fused_conv  # noqa: F401 — registers the kernels
    if not training or use_global_stats:
        scale, bias = _bn_scale_bias(gamma, beta, moving_mean, moving_var,
                                     eps, fix_gamma)
        out = _conv_bn_relu_infer(data, weight, scale, bias, kernel, stride,
                                  dilate, pad, num_group, residual)
        return (out, moving_mean, moving_var)
    z = _conv_core(data, weight, kernel, stride, dilate, pad,
                   num_group).astype(data.dtype)
    red = (0,) + tuple(range(2, z.ndim))
    mean, var = _bn_batch_stats(z, red)
    mean = mean.astype(moving_mean.dtype)
    var = var.astype(moving_var.dtype)
    scale, bias = _bn_scale_bias(gamma, beta, mean, var, eps, fix_gamma)
    if residual is None:
        out = tune.tuned_call("bn_act", _bn_act_xla, z, scale, bias)
    else:
        out = tune.tuned_call("bn_add_act", _bn_add_act_xla, z, scale, bias,
                              residual)
    return (out, mean, var)


@register(name="LayerNorm", aliases=("layer_norm",))
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    ax = axis % data.ndim
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    out = (data - mean) * lax.rsqrt(var + eps) * jnp.reshape(gamma, shape) + \
        jnp.reshape(beta, shape)
    if output_mean_var:
        return (out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax))
    return out


@register(name="InstanceNorm", aliases=("instance_norm",))
def instance_norm(data, gamma, beta, *, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * jnp.reshape(gamma, shape) + \
        jnp.reshape(beta, shape)


@register(name="GroupNorm", aliases=("group_norm",))
def group_norm(data, gamma, beta, *, num_groups=1, eps=1e-5):
    b, c = data.shape[0], data.shape[1]
    x = jnp.reshape(data, (b, num_groups, c // num_groups) + data.shape[2:])
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    # gamma/beta are per-GROUP, shape (num_groups,), applied in the grouped
    # view (reference group_norm-inl.h:163-171 reshapes gamma to
    # (1, num_groups, 1, ...) against the temp grouped data shape)
    pshape = (1, num_groups) + (1,) * (x.ndim - 2)
    x = x * jnp.reshape(gamma, pshape) + jnp.reshape(beta, pshape)
    return jnp.reshape(x, data.shape)


@register(name="LRN", aliases=("lrn",))
def lrn(data, *, nsize, alpha=1e-4, beta=0.75, knorm=2.0):
    """Reference src/operator/nn/lrn.cc — cross-channel local response norm."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    window = jnp.stack([padded[:, i:i + data.shape[1]] for i in range(nsize)], 0).sum(0)
    return data / jnp.power(knorm + alpha * window / nsize, beta)


# --------------------------------------------------------------------------
# Activations (reference src/operator/nn/activation.cc, leaky_relu.cc)
# --------------------------------------------------------------------------

@register(name="Activation", aliases=("activation",))
def activation(data, *, act_type):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise MXNetError(f"unknown act_type {act_type}")


@register(name="LeakyReLU", aliases=("leaky_relu",), stateful=True)
def leaky_relu(data, gamma=None, *, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, rng=None):
    """Reference src/operator/leaky_relu.cc: leaky/prelu/rrelu/elu/selu/gelu."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim:
            g = jnp.reshape(g, (1, -1) + (1,) * (data.ndim - 2)) if g.size > 1 else g
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        # eval mode uses the mean slope (reference leaky_relu-inl.h)
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise MXNetError(f"unknown act_type {act_type}")


# --------------------------------------------------------------------------
# Softmax family (reference src/operator/nn/softmax.cc, softmax_output.cc)
# --------------------------------------------------------------------------

@register(name="softmax")
def softmax(data, length=None, *, axis=-1, temperature=None, dtype=None,
            use_length=False):
    x = data / temperature if temperature else data
    if length is not None and use_length:
        T = data.shape[axis]
        steps = jnp.arange(T)
        mask_shape = [1] * data.ndim
        mask_shape[axis] = T
        mask = steps.reshape(mask_shape) < jnp.expand_dims(length.astype(jnp.int32), axis)
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if length is not None and use_length:
        out = jnp.where(mask, out, 0.0)
    return out.astype(dtype_np(dtype)) if dtype else out


@register(name="log_softmax")
def log_softmax(data, *, axis=-1, temperature=None, dtype=None, use_length=False):
    x = data / temperature if temperature else data
    out = jax.nn.log_softmax(x, axis=axis)
    return out.astype(dtype_np(dtype)) if dtype else out


@register(name="softmin")
def softmin(data, *, axis=-1, temperature=None, dtype=None):
    return jax.nn.softmax(-(data / temperature if temperature else data), axis=axis)


@register(name="SoftmaxActivation")
def softmax_activation(data, *, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    from .tensor_ops import flatten
    return jax.nn.softmax(flatten.fn(data), axis=-1).reshape(data.shape)


@register(name="SoftmaxOutput", aliases=("softmax_output", "Softmax"))
def softmax_output(data, label, *, grad_scale=1.0, ignore_label=-1.0,
                   use_ignore=False, multi_output=False, preserve_shape=False,
                   normalization="null", smooth_alpha=0.0, out_grad=False):
    """The defining quirk of SoftmaxOutput (reference softmax_output-inl.h):
    backward ignores the incoming gradient and emits (p - onehot(label))."""
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def f(x, y):
        return jax.nn.softmax(x, axis=axis)

    def fwd(x, y):
        return f(x, y), (f(x, y), y)

    def bwd(res, g):
        out, y = res
        nclass = out.shape[axis]
        oh = jax.nn.one_hot(y.astype(jnp.int32), nclass, axis=axis)
        if smooth_alpha:
            oh = oh * (1 - smooth_alpha) + smooth_alpha / (nclass - 1) * (1 - oh)
        grad = out - oh
        if use_ignore:
            keep = (y != ignore_label).astype(out.dtype)
            grad = grad * jnp.expand_dims(keep, axis)
        if normalization == "valid" and use_ignore:
            denom = jnp.maximum(jnp.sum(y != ignore_label), 1).astype(out.dtype)
            grad = grad / denom
        elif normalization == "batch":
            grad = grad / out.shape[0]
        return (grad * grad_scale, jnp.zeros_like(y))

    f.defvjp(fwd, bwd)
    return f(data, label)


@register(name="softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """Reference src/operator/loss_binary_op.cc."""
    logp = jax.nn.log_softmax(data, axis=-1)
    lbl = label.astype(jnp.int32)
    return -jnp.sum(jnp.take_along_axis(logp, lbl[:, None], axis=-1))


@register(name="LinearRegressionOutput", aliases=("linear_regression_output",))
def linear_regression_output(data, label, *, grad_scale=1.0):
    return _regression_output(data, label, grad_scale, "linear")


@register(name="MAERegressionOutput", aliases=("mae_regression_output",))
def mae_regression_output(data, label, *, grad_scale=1.0):
    return _regression_output(data, label, grad_scale, "mae")


@register(name="LogisticRegressionOutput", aliases=("logistic_regression_output",))
def logistic_regression_output(data, label, *, grad_scale=1.0):
    return _regression_output(data, label, grad_scale, "logistic")


def _regression_output(data, label, grad_scale, kind):
    """Reference src/operator/regression_output.cc: forward is identity /
    sigmoid; backward is (pred - label) / batch * grad_scale."""

    @jax.custom_vjp
    def f(x, y):
        return jax.nn.sigmoid(x) if kind == "logistic" else x

    def fwd(x, y):
        return f(x, y), (x, y)

    def bwd(res, g):
        x, y = res
        pred = jax.nn.sigmoid(x) if kind == "logistic" else x
        diff = pred - jnp.reshape(y, x.shape)
        if kind == "mae":
            diff = jnp.sign(diff)
        return (diff * grad_scale / x.shape[0], jnp.zeros_like(y))

    f.defvjp(fwd, bwd)
    return f(data, label)


# --------------------------------------------------------------------------
# Dropout (reference src/operator/nn/dropout.cc) — stateful (PRNG key)
# --------------------------------------------------------------------------

@register(name="Dropout", aliases=("dropout",), stateful=True, train_aware=True)
def dropout_op(data, *, p=0.5, mode="training", axes=(), cudnn_off=False,
               training=False, rng=None):
    if (not training and mode != "always") or p == 0:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, tuple(shape)).astype(data.dtype) / keep
    return data * mask


# --------------------------------------------------------------------------
# Up/Down sampling (reference src/operator/nn/upsampling.cc,
# contrib/bilinear_resize.cc)
# --------------------------------------------------------------------------

@register(name="UpSampling")
def upsampling(*data, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat", workspace=512):
    x = data[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    else:
        b, c, h, w = x.shape
        out = jax.image.resize(x, (b, c, h * scale, w * scale), method="bilinear")
    if len(data) > 1 and multi_input_mode == "concat":
        outs = [out]
        for d in data[1:]:
            s = out.shape[2] // d.shape[2]
            outs.append(jnp.repeat(jnp.repeat(d, s, axis=2), s, axis=3))
        return jnp.concatenate(outs, axis=1)
    return out


@register(name="BilinearResize2D")
def bilinear_resize_2d(data, *, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size", align_corners=True):
    b, c, h, w = data.shape
    if scale_height is not None:
        height, width = int(h * scale_height), int(w * scale_width)
    return jax.image.resize(data, (b, c, height, width), method="bilinear")


@register(name="Moments", aliases=("moments",))
def moments(data, *, axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None
    return (jnp.mean(data, axis=ax, keepdims=keepdims),
            jnp.var(data, axis=ax, keepdims=keepdims))


# --------------------------------------------------------------------------
# CTC loss (reference src/operator/nn/ctc_loss.cc / 3rdparty warpctc)
# --------------------------------------------------------------------------

@register(name="CTCLoss", aliases=("ctc_loss",))
def ctc_loss(data, label, data_lengths=None, label_lengths=None, *,
             use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """CTC via optax (jax-native forward-backward; reference uses warp-ctc).
    data: (T, B, C) alphabet incl. blank; label: (B, L)."""
    import optax
    T, B, C = data.shape
    logits = jnp.transpose(data, (1, 0, 2))  # (B, T, C)
    if blank_label == "first":
        # optax expects blank id 0 — matches "first"
        labels = label.astype(jnp.int32)
        blank_id = 0
    else:
        labels = label.astype(jnp.int32)
        blank_id = C - 1
    logit_pad = jnp.zeros((B, T), jnp.float32)
    if use_data_lengths and data_lengths is not None:
        steps = jnp.arange(T)[None, :]
        logit_pad = (steps >= data_lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)
    if use_label_lengths and label_lengths is not None:
        lsteps = jnp.arange(labels.shape[1])[None, :]
        label_pad = (lsteps >= label_lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)
    else:
        label_pad = (labels == (0 if blank_label == "first" else -1)).astype(jnp.float32) * 0
    loss = optax.ctc_loss(logits, logit_pad, labels, label_pad, blank_id=blank_id)
    return loss
