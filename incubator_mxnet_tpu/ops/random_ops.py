"""Random samplers (reference src/operator/random/: sample_op.cc, multisample,
shuffle.cc; per-device RNG resource include/mxnet/random_generator.h).

TPU-native redesign: the reference keeps mutable per-device Philox states
handed out by the ResourceManager; here every sampler is a pure function of a
jax PRNG key. The framework-level key chain lives in ndarray/random.py
(split-per-call), which is the functional equivalent of the reference's
per-device stateful generators and is what makes samplers safe under jit and
across a device mesh.
"""
from __future__ import annotations

from ..base import dtype_np
from .registry import register

import jax
import jax.numpy as jnp


@register(name="_random_uniform", aliases=("uniform",), stateful=True, nondiff=True)
def _random_uniform(*, low=0.0, high=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.uniform(rng, tuple(shape), dtype_np(dtype), low, high)


@register(name="_random_normal", aliases=("normal",), stateful=True, nondiff=True)
def _random_normal(*, loc=0.0, scale=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.normal(rng, tuple(shape), dtype_np(dtype)) * scale + loc


@register(name="_random_gamma", stateful=True, nondiff=True)
def _random_gamma(*, alpha=1.0, beta=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.gamma(rng, alpha, tuple(shape), dtype_np(dtype)) * beta


@register(name="_random_exponential", stateful=True, nondiff=True)
def _random_exponential(*, lam=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.exponential(rng, tuple(shape), dtype_np(dtype)) / lam


@register(name="_random_poisson", stateful=True, nondiff=True)
def _random_poisson(*, lam=1.0, shape=(1,), dtype="float32", rng=None):
    return jax.random.poisson(rng, lam, tuple(shape)).astype(dtype_np(dtype))


@register(name="_random_negative_binomial", stateful=True, nondiff=True)
def _random_negative_binomial(*, k=1, p=1.0, shape=(1,), dtype="float32", rng=None):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(dtype_np(dtype))


@register(name="_random_generalized_negative_binomial", stateful=True, nondiff=True)
def _random_gnb(*, mu=1.0, alpha=1.0, shape=(1,), dtype="float32", rng=None):
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(dtype_np(dtype))


@register(name="_random_randint", stateful=True, nondiff=True)
def _random_randint(*, low=0, high=1, shape=(1,), dtype="int32", rng=None):
    return jax.random.randint(rng, tuple(shape), low, high, dtype_np(dtype))


@register(name="_sample_multinomial", stateful=True, nondiff=True)
def _sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32", rng=None):
    """data: (..., K) probabilities; draw `shape` samples per distribution
    (reference src/operator/random/sample_multinomial_op.cc)."""
    n = 1
    for s in (shape if isinstance(shape, (tuple, list)) else (shape,)):
        n *= max(int(s), 1)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    out_shape = data.shape[:-1] + ((n,) if shape else ())
    draws = jax.random.categorical(rng, logits, axis=-1,
                                   shape=(n,) + data.shape[:-1])
    if data.ndim == 1:
        samp = draws if shape else draws[0]
    else:
        samp = jnp.moveaxis(draws, 0, -1)
        if not shape:
            samp = samp[..., 0]
    samp = samp.astype(dtype_np(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jnp.log(jnp.maximum(data, 1e-37)),
            samp.astype(jnp.int32).reshape(data.shape[:-1] + (-1,)), axis=-1)
        return (samp, lp.reshape(samp.shape))
    return samp


@register(name="_shuffle", stateful=True, nondiff=True)
def _shuffle(data, *, rng=None):
    """Shuffle along first axis (reference src/operator/random/shuffle_op.cc)."""
    perm = jax.random.permutation(rng, data.shape[0])
    return data[perm]


@register(name="_sample_unique_zipfian", stateful=True, nondiff=True)
def _sample_unique_zipfian(*, range_max, shape=(1,), rng=None):
    u = jax.random.uniform(rng, tuple(shape))
    out = (jnp.exp(u * jnp.log(range_max + 1.0)) - 1.0).astype(jnp.int32)
    return jnp.clip(out, 0, range_max - 1)
