"""INT8 quantization operators.

Reference: src/operator/quantization/ (5,622 LoC): quantize(_v2)/
dequantize/requantize + quantized conv/FC with int8 inputs and int32
accumulation. TPU-native: int8 matmul/conv lower to the MXU via
lax.dot_general/conv with preferred_element_type=int32 — the same
int8-in/int32-accum contract cuDNN/MKLDNN give the reference.
Affine scheme matches the reference: symmetric int8 ([-127, 127], zero
point 0) and asymmetric uint8 ([0, 255]).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

__all__ = []


def _ranges(out_type):
    if out_type == "int8":
        return -127.0, 127.0
    if out_type == "uint8":
        return 0.0, 255.0
    raise MXNetError(f"unsupported quantized dtype {out_type!r}")


@register(name="_contrib_quantize_v2", aliases=("quantize_v2",),
          nondiff=True)
def quantize_v2(data, *, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Reference quantize_v2-inl.h: affine-quantize fp32 -> int8/uint8
    with calibrated (or on-the-fly) ranges. Returns (qdata, min, max)."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx_ = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx_ = jnp.float32(max_calib_range)
    qmin, qmax = _ranges(out_type)
    if out_type == "int8":
        # symmetric: scale by max(|min|, |max|) (reference
        # quantize_v2 QuantizeToInt8)
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        scale = qmax / jnp.maximum(amax, 1e-30)
        q = jnp.clip(jnp.round(data * scale), qmin, qmax).astype(jnp.int8)
        return q, -amax, amax
    scale = (qmax - qmin) / jnp.maximum(mx_ - mn, 1e-30)
    q = jnp.clip(jnp.round((data - mn) * scale), qmin, qmax).astype(jnp.uint8)
    return q, mn, mx_


@register(name="_contrib_quantize", aliases=("quantize",), nondiff=True)
def quantize(data, min_range, max_range, *, out_type="uint8"):
    """Reference quantize-inl.h (explicit range arrays). Range inputs stay
    traced — this op runs jitted."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    qmin, qmax = _ranges(out_type)
    if out_type == "int8":
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        scale = qmax / jnp.maximum(amax, 1e-30)
        q = jnp.clip(jnp.round(data * scale), qmin, qmax).astype(jnp.int8)
        return q, -amax, amax
    scale = (qmax - qmin) / jnp.maximum(mx_ - mn, 1e-30)
    q = jnp.clip(jnp.round((data - mn) * scale), qmin, qmax).astype(jnp.uint8)
    return q, mn, mx_


@register(name="_contrib_dequantize", aliases=("dequantize",), nondiff=True)
def dequantize(qdata, min_range, max_range, *, out_type="float32"):
    """Reference dequantize-inl.h."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    if qdata.dtype == jnp.int8:
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        return qdata.astype(jnp.float32) * (amax / 127.0)
    if qdata.dtype == jnp.int32:
        # int32 accumulator from quantized_conv/FC: full-scale mapping
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        return qdata.astype(jnp.float32) * (amax / 2147483647.0)
    scale = (mx_ - mn) / 255.0
    return qdata.astype(jnp.float32) * scale + mn


@register(name="_contrib_requantize", aliases=("requantize",), nondiff=True)
def requantize(qdata, min_range, max_range, *, out_type="int8",
               min_calib_range=None, max_calib_range=None):
    """int32 accumulator -> int8 (reference requantize-inl.h)."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    real = qdata.astype(jnp.float32) * \
        (jnp.maximum(jnp.abs(mn), jnp.abs(mx_)) / 2147483647.0)
    if min_calib_range is not None and max_calib_range is not None:
        amax = max(abs(min_calib_range), abs(max_calib_range))
    else:
        amax = jnp.max(jnp.abs(real))
    q = jnp.clip(jnp.round(real * (127.0 / jnp.maximum(amax, 1e-30))),
                 -127, 127).astype(jnp.int8)
    return q, -jnp.asarray(amax, jnp.float32), jnp.asarray(amax, jnp.float32)


@register(name="_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",), nondiff=True)
def quantized_fully_connected(data, weight, bias, data_min, data_max,
                              weight_min, weight_max, bias_min=None,
                              bias_max=None, *, num_hidden=0, no_bias=False,
                              flatten=True):
    """int8 x int8 -> int32 matmul on the MXU (reference
    quantized_fully_connected.cc). Returns (out_i32, out_min, out_max)."""
    x = data
    if flatten and x.ndim > 2:
        x = jnp.reshape(x, (x.shape[0], -1))
    out = lax.dot_general(x, weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(data_min), jnp.abs(data_max)).reshape(())
    w_amax = jnp.maximum(jnp.abs(weight_min), jnp.abs(weight_max)).reshape(())
    out_amax = d_amax * w_amax * (2147483647.0 / (127.0 * 127.0))
    if bias is not None and not no_bias:
        b_amax = jnp.maximum(jnp.abs(bias_min), jnp.abs(bias_max)).reshape(())
        # rescale bias into the output's int32 scale
        b_real = bias.astype(jnp.float32) * (b_amax / 127.0)
        scale = 2147483647.0 / jnp.maximum(out_amax, 1e-30)
        out = out + jnp.round(b_real * scale).astype(jnp.int32)
    return out, -out_amax, out_amax


@register(name="_contrib_quantized_conv", aliases=("quantized_conv",),
          nondiff=True)
def quantized_conv(data, weight, bias, data_min, data_max, weight_min,
                   weight_max, bias_min=None, bias_max=None, *, kernel,
                   stride=(), dilate=(), pad=(), num_filter=0, num_group=1,
                   no_bias=False, layout=None, workspace=1024,
                   cudnn_tune=None, cudnn_off=False):
    """int8 convolution with int32 accumulation (reference
    quantized_conv.cc). NCHW, weight OIHW like the fp op."""
    nd_ = len(kernel)
    stride = tuple(stride) or (1,) * nd_
    dilate = tuple(dilate) or (1,) * nd_
    pad = tuple(pad) or (0,) * nd_
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    ("NCHW", "OIHW", "NCHW") if nd_ == 2 else
                                    ("NCW", "OIW", "NCW"))
    out = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(data_min), jnp.abs(data_max)).reshape(())
    w_amax = jnp.maximum(jnp.abs(weight_min), jnp.abs(weight_max)).reshape(())
    out_amax = d_amax * w_amax * (2147483647.0 / (127.0 * 127.0))
    if bias is not None and not no_bias:
        b_amax = jnp.maximum(jnp.abs(bias_min), jnp.abs(bias_max)).reshape(())
        b_real = bias.astype(jnp.float32) * (b_amax / 127.0)
        scale = 2147483647.0 / jnp.maximum(out_amax, 1e-30)
        out = out + jnp.round(b_real * scale).astype(jnp.int32).reshape(
            (1, -1) + (1,) * nd_)
    return out, -out_amax, out_amax
