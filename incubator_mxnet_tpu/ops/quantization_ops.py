"""INT8 quantization operators.

Reference: src/operator/quantization/ (5,622 LoC): quantize(_v2)/
dequantize/requantize + quantized conv/FC with int8 inputs and int32
accumulation. TPU-native: int8 matmul/conv lower to the MXU via
lax.dot_general/conv with preferred_element_type=int32 — the same
int8-in/int32-accum contract cuDNN/MKLDNN give the reference.
Affine scheme matches the reference: symmetric int8 ([-127, 127], zero
point 0) and asymmetric uint8 ([0, 255]).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

__all__ = []


def _ranges(out_type):
    if out_type == "int8":
        return -127.0, 127.0
    if out_type == "uint8":
        return 0.0, 255.0
    raise MXNetError(f"unsupported quantized dtype {out_type!r}")


@register(name="_contrib_quantize_v2", aliases=("quantize_v2",),
          nondiff=True)
def quantize_v2(data, *, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Reference quantize_v2-inl.h: affine-quantize fp32 -> int8/uint8
    with calibrated (or on-the-fly) ranges. Returns (qdata, min, max)."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx_ = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx_ = jnp.float32(max_calib_range)
    qmin, qmax = _ranges(out_type)
    if out_type == "int8":
        # symmetric: scale by max(|min|, |max|) (reference
        # quantize_v2 QuantizeToInt8)
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        scale = qmax / jnp.maximum(amax, 1e-30)
        q = jnp.clip(jnp.round(data * scale), qmin, qmax).astype(jnp.int8)
        return q, -amax, amax
    scale = (qmax - qmin) / jnp.maximum(mx_ - mn, 1e-30)
    q = jnp.clip(jnp.round((data - mn) * scale), qmin, qmax).astype(jnp.uint8)
    return q, mn, mx_


@register(name="_contrib_quantize", aliases=("quantize",), nondiff=True)
def quantize(data, min_range, max_range, *, out_type="uint8"):
    """Reference quantize-inl.h (explicit range arrays). Range inputs stay
    traced — this op runs jitted."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    qmin, qmax = _ranges(out_type)
    if out_type == "int8":
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        scale = qmax / jnp.maximum(amax, 1e-30)
        q = jnp.clip(jnp.round(data * scale), qmin, qmax).astype(jnp.int8)
        return q, -amax, amax
    scale = (qmax - qmin) / jnp.maximum(mx_ - mn, 1e-30)
    q = jnp.clip(jnp.round((data - mn) * scale), qmin, qmax).astype(jnp.uint8)
    return q, mn, mx_


@register(name="_contrib_dequantize", aliases=("dequantize",), nondiff=True)
def dequantize(qdata, min_range, max_range, *, out_type="float32"):
    """Reference dequantize-inl.h."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    if qdata.dtype == jnp.int8:
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        return qdata.astype(jnp.float32) * (amax / 127.0)
    if qdata.dtype == jnp.int32:
        # int32 accumulator from quantized_conv/FC: full-scale mapping
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
        return qdata.astype(jnp.float32) * (amax / 2147483647.0)
    scale = (mx_ - mn) / 255.0
    return qdata.astype(jnp.float32) * scale + mn


@register(name="_contrib_requantize", aliases=("requantize",), nondiff=True)
def requantize(qdata, min_range, max_range, *, out_type="int8",
               min_calib_range=None, max_calib_range=None):
    """int32 accumulator -> int8 (reference requantize-inl.h)."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    real = qdata.astype(jnp.float32) * \
        (jnp.maximum(jnp.abs(mn), jnp.abs(mx_)) / 2147483647.0)
    if min_calib_range is not None and max_calib_range is not None:
        amax = max(abs(min_calib_range), abs(max_calib_range))
    else:
        amax = jnp.max(jnp.abs(real))
    q = jnp.clip(jnp.round(real * (127.0 / jnp.maximum(amax, 1e-30))),
                 -127, 127).astype(jnp.int8)
    return q, -jnp.asarray(amax, jnp.float32), jnp.asarray(amax, jnp.float32)


def _split_q_args(rest, no_bias):
    """(bias, dmin, dmax, wmin, wmax, bmin, bmax) from the positional
    tail, which omits every bias slot when the fp32 op had no bias."""
    if no_bias:
        r = rest[1:] if rest and rest[0] is None else rest
        dmin, dmax, wmin, wmax = r[:4]
        return None, dmin, dmax, wmin, wmax, None, None
    if len(rest) < 7:
        raise MXNetError(
            "quantized op with a bias needs bias_min and bias_max "
            "(positional tail: bias, data_min, data_max, weight_min, "
            "weight_max, bias_min, bias_max); pass no_bias=True to omit "
            "the bias slots")
    return rest[:7]


@register(name="_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",), nondiff=True)
def quantized_fully_connected(data, weight, *rest, num_hidden=0,
                              no_bias=False, flatten=True):
    """int8 x int8 -> int32 matmul on the MXU (reference
    quantized_fully_connected.cc). Positional tail: bias?, data_min,
    data_max, weight_min, weight_max, bias_min?, bias_max? (bias slots
    omitted under no_bias). Returns (out_i32, out_min, out_max)."""
    bias, data_min, data_max, weight_min, weight_max, bias_min, bias_max = \
        _split_q_args(rest, no_bias)
    x = data
    if flatten and x.ndim > 2:
        x = jnp.reshape(x, (x.shape[0], -1))
    out = lax.dot_general(x, weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(data_min), jnp.abs(data_max)).reshape(())
    w_amax = jnp.maximum(jnp.abs(weight_min), jnp.abs(weight_max)).reshape(())
    out_amax = d_amax * w_amax * (2147483647.0 / (127.0 * 127.0))
    if bias is not None and not no_bias:
        b_amax = jnp.maximum(jnp.abs(bias_min), jnp.abs(bias_max)).reshape(())
        # rescale bias into the output's int32 scale
        b_real = bias.astype(jnp.float32) * (b_amax / 127.0)
        scale = 2147483647.0 / jnp.maximum(out_amax, 1e-30)
        out = out + jnp.round(b_real * scale).astype(jnp.int32)
    return out, -out_amax, out_amax


@register(name="_contrib_quantized_conv", aliases=("quantized_conv",),
          nondiff=True)
def quantized_conv(data, weight, *rest, kernel,
                   stride=(), dilate=(), pad=(), num_filter=0, num_group=1,
                   no_bias=False, layout=None, workspace=1024,
                   cudnn_tune=None, cudnn_off=False):
    """int8 convolution with int32 accumulation (reference
    quantized_conv.cc). NCHW, weight OIHW like the fp op; positional tail
    as in quantized_fully_connected."""
    bias, data_min, data_max, weight_min, weight_max, bias_min, bias_max = \
        _split_q_args(rest, no_bias)
    nd_ = len(kernel)
    stride = tuple(stride) or (1,) * nd_
    dilate = tuple(dilate) or (1,) * nd_
    pad = tuple(pad) or (0,) * nd_
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    ("NCHW", "OIHW", "NCHW") if nd_ == 2 else
                                    ("NCW", "OIW", "NCW"))
    out = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    d_amax = jnp.maximum(jnp.abs(data_min), jnp.abs(data_max)).reshape(())
    w_amax = jnp.maximum(jnp.abs(weight_min), jnp.abs(weight_max)).reshape(())
    out_amax = d_amax * w_amax * (2147483647.0 / (127.0 * 127.0))
    if bias is not None and not no_bias:
        b_amax = jnp.maximum(jnp.abs(bias_min), jnp.abs(bias_max)).reshape(())
        b_real = bias.astype(jnp.float32) * (b_amax / 127.0)
        scale = 2147483647.0 / jnp.maximum(out_amax, 1e-30)
        out = out + jnp.round(b_real * scale).astype(jnp.int32).reshape(
            (1, -1) + (1,) * nd_)
    return out, -out_amax, out_amax


# ---------------------------------------------------------------------------
# Quantized op tail (reference src/operator/quantization/
# quantized_pooling.cc, quantized_flatten.cc, quantized_activation.cc,
# quantized_elemwise_add.cc, quantized_concat.cc, quantized_batch_norm.cc):
# shape/range-preserving ops that keep a chain in int8 between the
# matmul/conv sandwiches instead of bouncing through fp32.
# ---------------------------------------------------------------------------

@register(name="_contrib_quantized_pooling", aliases=("quantized_pooling",),
          nondiff=True)
def quantized_pooling(data, min_range, max_range, *, kernel=(), stride=(),
                      pad=(), pool_type="max", global_pool=False,
                      pooling_convention="valid", count_include_pad=True,
                      layout=None, cudnn_off=False, p_value=2):
    """Pool directly on the int8 lattice. max-pool is exact (max of codes
    = code of max); avg-pool averages codes with round-to-nearest, the
    reference's behavior. Ranges pass through unchanged."""
    from .nn_ops import pooling as _pool_op
    pooling = _pool_op.fn
    if pool_type == "max":
        out = pooling(data, kernel=kernel, pool_type="max",
                      global_pool=global_pool, stride=stride, pad=pad,
                      pooling_convention=pooling_convention)
        return out, min_range, max_range
    if pool_type != "avg":
        raise MXNetError(f"quantized pooling supports max/avg, "
                         f"got {pool_type!r}")
    f = pooling(data.astype(jnp.float32), kernel=kernel, pool_type="avg",
                global_pool=global_pool, stride=stride, pad=pad,
                pooling_convention=pooling_convention,
                count_include_pad=count_include_pad)
    return jnp.round(f).astype(data.dtype), min_range, max_range


@register(name="_contrib_quantized_flatten", aliases=("quantized_flatten",),
          nondiff=True)
def quantized_flatten(data, min_range, max_range):
    import math
    tail = math.prod(data.shape[1:])   # explicit: -1 breaks on 0-size batch
    return (jnp.reshape(data, (data.shape[0], tail)), min_range, max_range)


@register(name="_contrib_quantized_act", aliases=("quantized_act",),
          nondiff=True)
def quantized_act(data, min_range, max_range, *, act_type="relu"):
    """relu on symmetric int8/int32 codes: clamp negatives to the zero
    point (0). The representable range keeps its magnitude so downstream
    scales are unchanged (reference quantized_activation.cc)."""
    if act_type != "relu":
        raise MXNetError("only relu is supported quantized")
    return jnp.maximum(data, 0), min_range, max_range


@register(name="_contrib_quantized_elemwise_add",
          aliases=("quantized_elemwise_add",), nondiff=True)
def quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """int8 + int8 with independent scales -> int32 out
    (reference quantized_elemwise_add.cc): both operands are rescaled to
    the output's int32 scale, whose range is the sum of the input
    magnitudes (the exact bound for a sum)."""
    def code_max(x):
        # int8 codes span +/-127, int32 (a conv/fc accumulator) the full
        # int32 scale — the dequantization factor differs accordingly
        return 127.0 if x.dtype == jnp.int8 else 2147483647.0

    l_amax = jnp.maximum(jnp.abs(lhs_min), jnp.abs(lhs_max)).reshape(())
    r_amax = jnp.maximum(jnp.abs(rhs_min), jnp.abs(rhs_max)).reshape(())
    out_amax = l_amax + r_amax
    scale = 2147483647.0 / jnp.maximum(out_amax, 1e-30)
    lf = lhs.astype(jnp.float32) * (l_amax / code_max(lhs))
    rf = rhs.astype(jnp.float32) * (r_amax / code_max(rhs))
    out = jnp.clip(jnp.round((lf + rf) * scale), -2147483647, 2147483647)
    return out.astype(jnp.int32), -out_amax, out_amax


@register(name="_contrib_quantized_concat", aliases=("quantized_concat",),
          nondiff=True)
def quantized_concat(*args, dim=1, num_args=None):
    """Concat n int8 inputs with per-input ranges: the output range is the
    widest input range and every input is rescaled onto it (reference
    quantized_concat.cc). args = data_0..data_{n-1}, then
    min_0, max_0, min_1, max_1, ..."""
    n = num_args or (len(args) // 3)
    datas = args[:n]
    mins = args[n::2]
    maxs = args[n + 1::2]
    amaxs = [jnp.maximum(jnp.abs(lo), jnp.abs(hi)).reshape(())
             for lo, hi in zip(mins, maxs)]
    out_amax = amaxs[0]
    for a in amaxs[1:]:
        out_amax = jnp.maximum(out_amax, a)
    scaled = [jnp.clip(jnp.round(d.astype(jnp.float32) * (a / out_amax)),
                       -127, 127).astype(jnp.int8)
              for d, a in zip(datas, amaxs)]
    return jnp.concatenate(scaled, axis=dim), -out_amax, out_amax


@register(name="_contrib_quantized_batch_norm",
          aliases=("quantized_batch_norm",), nondiff=True)
def quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                         min_range, max_range, *, eps=1e-3, momentum=0.9,
                         fix_gamma=False, use_global_stats=True, axis=1,
                         output_mean_var=False, cudnn_off=False):
    """Inference BatchNorm on int8 codes (reference
    quantized_batchnorm.cc): the running-stat affine a*x+b is applied per
    channel in the real domain and the result is requantized to int8 with
    the affine image of the input range as the new range."""
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)).reshape(())
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    a = g * lax.rsqrt(moving_var + eps)
    b = beta - moving_mean * a
    shape = [1] * data.ndim
    shape[axis % data.ndim] = data.shape[axis % data.ndim]
    real = data.astype(jnp.float32) * (amax / 127.0)
    out_real = real * jnp.reshape(a, shape) + jnp.reshape(b, shape)
    # exact affine image of [-amax, amax] per channel, then the global hull
    out_amax = jnp.max(jnp.abs(a) * amax + jnp.abs(b))
    q = jnp.clip(jnp.round(out_real * (127.0 / jnp.maximum(out_amax, 1e-30))),
                 -127, 127).astype(jnp.int8)
    return q, -out_amax, out_amax


@register(name="_contrib_rescale_int8", aliases=("rescale_int8",),
          nondiff=True)
def rescale_int8(qdata, min_range, max_range, *, out_type="int8",
                 min_calib_range=None, max_calib_range=None):
    """int8 -> int8 range bridge: re-express codes quantized for
    (min_range, max_range) in the target calib range WITHOUT an fp32
    tensor round trip. Replaces the reference's dequantize+quantize_v2
    pair between consecutive int8 consumers (quantize_graph_pass.cc
    inserts that pair; here the fp32 intermediate would be the single
    largest HBM cost of the int8 graph — elementwise on codes, XLA fuses
    it into the consumer's input read)."""
    if out_type != "int8":
        raise MXNetError("rescale_int8 bridges symmetric int8 codes only; "
                         f"got out_type={out_type!r} (the affine uint8 "
                         "form would need a zero-point path)")
    if qdata.dtype != jnp.int8:
        raise MXNetError("rescale_int8 expects int8 codes; got "
                         f"{qdata.dtype} — int32 accumulators take "
                         "_contrib_requantize")
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx_ = jnp.asarray(max_range, jnp.float32).reshape(())
    amax_in = jnp.maximum(jnp.abs(mn), jnp.abs(mx_))
    if min_calib_range is not None and max_calib_range is not None:
        amax_out = jnp.float32(max(abs(min_calib_range),
                                   abs(max_calib_range)))
        lo, hi = -amax_out, amax_out
    else:
        amax_out = amax_in
        lo, hi = -amax_in, amax_in
    scale = amax_in / jnp.maximum(amax_out, 1e-30)
    q = jnp.clip(jnp.round(qdata.astype(jnp.float32) * scale),
                 -127, 127).astype(jnp.int8)
    return q, lo, hi
