"""Operator long tail: the last reference-parity ops outside the family files.

Reference: src/operator/tensor/elemwise_sum.cc (add_n),
elemwise_unary_op_basic.cc (reshape_like), matrix_op.cc (_slice_assign),
la_op.cc (the linalg factorization/diag tail), init_op.cc (_linspace,
_zeros_without_dtype, _contrib_arange_like), contrib/bounding_box.cc
(_contrib_bipartite_matching), contrib/sync_batch_norm-inl.h
(SyncBatchNorm), sparse_retain.cc, square_sum-inl.h.

Each op is ONE pure jax function; gradients via jax.vjp like the rest of
the registry. Host-sequential algorithms (bipartite matching) run eager
like the DGL family — the reference registers them as CPU kernels too.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .registry import register

import jax
import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------------------
# elementwise-sum / reshape / assignment tail (tensor/)
# ---------------------------------------------------------------------------

@register(name="add_n", aliases=("ElementWiseSum", "_sum"))
def add_n(*args, num_args=None):
    """Sum of N arrays (reference src/operator/tensor/elemwise_sum.cc:1)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register(name="_rnn_param_concat")
def _rnn_param_concat(*args, dim=0, num_args=None):
    """RNN parameter flattening concat (reference
    src/operator/nn/concat.cc _rnn_param_concat): plain concat along
    `dim`, kept as its own name for symbol-JSON parity."""
    return jnp.concatenate(args, axis=dim)


@register(name="reshape_like")
def reshape_like(lhs, rhs, *, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape over an index window (reference
    src/operator/tensor/elemwise_unary_op_basic.cc:1 ReshapeLikeParam)."""
    def _win(nd_, b, e):
        b = 0 if b is None else (b + nd_ if b < 0 else b)
        e = nd_ if e is None else (e + nd_ if e < 0 else e)
        return b, e
    lb, le = _win(lhs.ndim, lhs_begin, lhs_end)
    rb, re_ = _win(rhs.ndim, rhs_begin, rhs_end)
    shape = lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:]
    if int(_np.prod(shape, dtype=_np.int64)) != lhs.size:
        raise MXNetError(
            f"reshape_like: target shape {shape} does not match lhs size "
            f"{lhs.size}")
    return jnp.reshape(lhs, shape)


def _slice_window(shape, begin, end, step):
    idx = []
    for i in range(len(begin)):
        s = (step[i] if step is not None and i < len(step)
             and step[i] is not None else 1)
        idx.append(slice(begin[i], end[i], s))
    while len(idx) < len(shape):
        idx.append(slice(None))
    return tuple(idx)


@register(name="_slice_assign", aliases=("_crop_assign",))
def _slice_assign(lhs, rhs, *, begin, end, step=None):
    """Copy of lhs with lhs[begin:end:step] = rhs (reference
    src/operator/tensor/matrix_op.cc:532 — the in-place `x[idx] = y`
    lowering; functional out-of-place here for XLA)."""
    return lhs.at[_slice_window(lhs.shape, begin, end, step)].set(
        rhs.astype(lhs.dtype))


@register(name="_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(data, *, scalar=0.0, begin=(), end=(), step=None):
    """Reference src/operator/tensor/matrix_op.cc:557."""
    return data.at[_slice_window(data.shape, begin, end, step)].set(
        jnp.asarray(scalar, data.dtype))


@register(name="_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs; rhs only pins shape/storage attrs (reference
    elemwise_unary_op_basic.cc — used by the sparse grad plumbing)."""
    return lhs


@register(name="_square_sum", aliases=("square_sum",))
def _square_sum(data, *, axis=None, keepdims=False, exclude=False):
    """sum(x**2) fused reduction (reference src/operator/tensor/
    square_sum-inl.h — the row_sparse fast path is moot here: XLA fuses
    square into the reduce)."""
    ax = None if axis is None else (tuple(axis) if isinstance(
        axis, (tuple, list)) else (axis,))
    if exclude and ax is not None:
        ax = tuple(i for i in range(data.ndim) if i not in
                   tuple(a % data.ndim for a in ax))
    return jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims)


@register(name="_sparse_retain", nondiff=True)
def _sparse_retain(data, indices):
    """Dense view of row-retention (reference
    src/operator/tensor/sparse_retain.cc:1): zero every row of `data`
    whose index is not in `indices`. The RowSparse-storage form lives on
    ndarray.sparse.retain; this op is the jit-compatible dense analog."""
    keep = jnp.zeros((data.shape[0],), jnp.bool_).at[
        indices.astype(jnp.int32)].set(True)
    return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


@register(name="hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    """Reference src/operator/tensor/elemwise_unary_op_basic.cc
    hard_sigmoid."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0).astype(data.dtype)


@register(name="_linspace", aliases=("linspace_op",), nondiff=True)
def _linspace(*, start, stop=None, num, endpoint=True, dtype="float32",
              ctx=None):
    """Reference src/operator/tensor/init_op.cc _linspace."""
    from ..base import dtype_np
    return jnp.linspace(start, stop if stop is not None else start, int(num),
                        endpoint=endpoint, dtype=dtype_np(dtype))


@register(name="_zeros_without_dtype", nondiff=True)
def _zeros_without_dtype(*, shape, ctx=None, dtype=None):
    """Reference src/operator/tensor/init_op.cc _zeros_without_dtype:
    zeros defaulting to float32 when no dtype is given."""
    from ..base import dtype_np
    return jnp.zeros(tuple(shape) if isinstance(shape, (tuple, list))
                     else (shape,), dtype_np(dtype or "float32"))


@register(name="arange_like", aliases=("_contrib_arange_like",),
          nondiff=True)
def arange_like(data, *, start=0.0, step=1.0, repeat=1, axis=None, ctx=None):
    """Reference src/operator/contrib/../tensor/init_op.cc:104
    _contrib_arange_like: arange shaped like `data` (flat, or along one
    axis)."""
    # RangeCompute semantics (reference init_op.h:518): out[i] = start +
    # (i // repeat) * step over EXACTLY n elements — a jnp.repeat of
    # arange(n // repeat) would truncate when repeat doesn't divide n
    if axis is None:
        n = data.size
        out = start + step * (jnp.arange(n, dtype=jnp.float32) // repeat)
        return out.reshape(data.shape).astype(data.dtype)
    ax = axis % data.ndim
    n = data.shape[ax]
    out = start + step * (jnp.arange(n, dtype=jnp.float32) // repeat)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# linalg factorization/diag tail (tensor/la_op.cc)
# ---------------------------------------------------------------------------

@register(name="linalg_syevd")
def linalg_syevd(a):
    """Eigendecomposition of symmetric A = U^T diag(L) U (reference
    src/operator/tensor/la_op.cc:1 _linalg_syevd; rows of U are the
    eigenvectors — the transpose of numpy's column convention)."""
    w, v = jnp.linalg.eigh(a)
    return (jnp.swapaxes(v, -1, -2), w)


@register(name="linalg_potri")
def linalg_potri(a):
    """Inverse of B = A A^T from its Cholesky factor A (reference
    la_op.cc _linalg_potri): B^-1 = A^-T A^-1."""
    import jax.scipy.linalg as jsl
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    ainv = jsl.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(ainv, -1, -2), ainv)


@register(name="linalg_slogdet")
def linalg_slogdet(a):
    """Reference la_op.cc _linalg_slogdet: (sign, log|det|)."""
    sign, logabs = jnp.linalg.slogdet(a)
    return (sign, logabs)


@register(name="linalg_gelqf")
def linalg_gelqf(a):
    """LQ factorization A = L Q with orthonormal rows of Q (reference
    la_op.cc _linalg_gelqf, requires m <= n): via QR of A^T."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
    # sign-normalize so L has a non-negative diagonal (LAPACK convention)
    d = jnp.sign(jnp.diagonal(r, axis1=-2, axis2=-1))
    d = jnp.where(d == 0, 1.0, d).astype(a.dtype)
    q = q * d[..., None, :]
    r = r * d[..., :, None]
    return (jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2))


@register(name="linalg_trmm")
def linalg_trmm(a, b, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matrix multiply out = alpha * op(tri(A)) B, or B op(A)
    when rightside (reference la_op.cc _linalg_trmm)."""
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b)
    return alpha * out


@register(name="linalg_extractdiag")
def linalg_extractdiag(a, *, offset=0):
    """Reference la_op.cc _linalg_extractdiag."""
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register(name="linalg_makediag")
def linalg_makediag(d, *, offset=0):
    """Reference la_op.cc _linalg_makediag."""
    n = d.shape[-1] + abs(offset)
    base = jnp.zeros(d.shape[:-1] + (n, n), d.dtype)
    idx = jnp.arange(d.shape[-1])
    r = idx + max(0, -offset)
    c = idx + max(0, offset)
    return base.at[..., r, c].set(d)


@register(name="linalg_extracttrian")
def linalg_extracttrian(a, *, offset=0, lower=True):
    """Flatten the triangle at `offset` into a vector, row-major
    (reference la_op.cc _linalg_extracttrian)."""
    n = a.shape[-1]
    rows, cols = _trian_indices(n, offset, lower)
    return a[..., rows, cols]


@register(name="linalg_maketrian")
def linalg_maketrian(d, *, offset=0, lower=True):
    """Inverse of extracttrian (reference la_op.cc _linalg_maketrian)."""
    k = d.shape[-1]
    # triangle of side m has m*(m+1)/2 entries; with |offset| the square
    # is m + |offset| wide
    m = int((_np.sqrt(8 * k + 1) - 1) / 2)
    n = m + abs(offset)
    rows, cols = _trian_indices(n, offset, lower)
    base = jnp.zeros(d.shape[:-1] + (n, n), d.dtype)
    return base.at[..., rows, cols].set(d)


def _trian_indices(n, offset, lower):
    if lower:
        return _np.tril_indices(n, offset)
    return _np.triu_indices(n, offset)


# ---------------------------------------------------------------------------
# bipartite matching (contrib/bounding_box.cc:158)
# ---------------------------------------------------------------------------

@register(name="bipartite_matching",
          aliases=("_contrib_bipartite_matching",), nondiff=True)
def bipartite_matching(data, *, threshold, is_ascend=False, topk=-1):
    """Greedy bipartite matching over a (..., rows, cols) score matrix
    (reference src/operator/contrib/bounding_box.cc:158 + bounding_box-inl.h
    struct bipartite_matching). Returns (row_match, col_match): for each
    row the matched col (or -1), and vice versa. The greedy scan is
    inherently sequential — lax.fori_loop over the sorted score list keeps
    it on-device with static shapes."""
    shape = data.shape
    rows_n, cols_n = shape[-2], shape[-1]
    flat = data.reshape((-1, rows_n * cols_n))

    def one(scores):
        order = jnp.argsort(-scores if not is_ascend else scores,
                            stable=True)

        def body(j, carry):
            rmark, cmark, count, stop = carry
            idx = order[j]
            s = scores[idx]
            r = idx // cols_n
            c = idx % cols_n
            good = jnp.where(is_ascend, s < threshold, s > threshold)
            free = jnp.logical_and(rmark[r] == -1, cmark[c] == -1)
            # the reference breaks at the first bad score among free pairs
            stop = jnp.logical_or(stop, jnp.logical_and(
                free, jnp.logical_not(good)))
            do = jnp.logical_and(jnp.logical_and(free, good),
                                 jnp.logical_not(stop))
            if topk > 0:
                # reference quirk (bounding_box-inl.h): it marks the pair
                # FIRST and then breaks when ++count > topk, so up to
                # topk+1 pairs get marked — count may reach topk before
                # the mark that trips the break
                do = jnp.logical_and(do, count <= topk)
            rmark = jnp.where(do, rmark.at[r].set(c), rmark)
            cmark = jnp.where(do, cmark.at[c].set(r), cmark)
            count = count + do.astype(jnp.int32)
            return (rmark, cmark, count, stop)

        init = (jnp.full((rows_n,), -1.0, data.dtype),
                jnp.full((cols_n,), -1.0, data.dtype),
                jnp.int32(0), jnp.bool_(False))
        rmark, cmark, _, _ = lax.fori_loop(0, rows_n * cols_n, body, init)
        return rmark, cmark

    r, c = jax.vmap(one)(flat)
    return (r.reshape(shape[:-2] + (rows_n,)),
            c.reshape(shape[:-2] + (cols_n,)))


# ---------------------------------------------------------------------------
# SyncBatchNorm (contrib/sync_batch_norm-inl.h:56)
# ---------------------------------------------------------------------------

@register(name="SyncBatchNorm", aliases=("_contrib_SyncBatchNorm",),
          train_aware=True)
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    output_mean_var=False, ndev=1, key="", axis_name=None,
                    training=False):
    """Cross-device BatchNorm (reference
    src/operator/contrib/sync_batch_norm-inl.h:56). The reference syncs
    batch statistics over `ndev` GPUs with a key-matched barrier; on TPU
    the sync is `lax.pmean` over the mesh axis named `axis_name` when the
    op runs inside shard_map/pmap — the SPMD program IS the barrier.
    Outside a mesh (axis_name=None) it reduces to single-device
    BatchNorm, which is exactly the reference semantics at ndev=1."""
    red = tuple(i for i in range(data.ndim) if i != 1)
    if training and not use_global_stats:
        mean = jnp.mean(data.astype(jnp.float32), axis=red)
        sq = jnp.mean(jnp.square(data.astype(jnp.float32)), axis=red)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            sq = lax.pmean(sq, axis_name)
        var = sq - jnp.square(mean)
    else:
        mean, var = moving_mean, moving_var
    shape = [1] * data.ndim
    shape[1] = data.shape[1]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    out = (data - jnp.reshape(mean, shape).astype(data.dtype)) * lax.rsqrt(
        jnp.reshape(var, shape).astype(data.dtype) + eps) \
        * jnp.reshape(g, shape).astype(data.dtype) \
        + jnp.reshape(beta, shape).astype(data.dtype)
    return (out, mean, var)


@register(name="SparseEmbedding", aliases=("_contrib_SparseEmbedding",))
def sparse_embedding(data, weight, *, input_dim, output_dim, dtype="float32",
                     deterministic=False):
    """Reference src/operator/tensor/indexing_op.cc SparseEmbedding: same
    lookup as Embedding; the 'sparse gradient' is a storage hint that has
    no analog under XLA (gather grads are scatter-adds already)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


# ---------------------------------------------------------------------------
# reference-name aliases with no existing registration
# (elemwise_binary_scalar_op_basic.cc uses _minus/_rminus; the sparse
# _scatter_* forms are identical computations with a storage hint)
# ---------------------------------------------------------------------------

@register(name="_minus_scalar")
def _minus_scalar(data, *, scalar):
    return data - jnp.asarray(scalar, data.dtype)


@register(name="_rminus_scalar")
def _rminus_scalar(data, *, scalar):
    return jnp.asarray(scalar, data.dtype) - data


@register(name="_hypot_scalar")
def _hypot_scalar(data, *, scalar):
    return jnp.hypot(data, jnp.asarray(scalar, data.dtype))


@register(name="_scatter_plus_scalar")
def _scatter_plus_scalar(data, *, scalar):
    """Reference elemwise_binary_scalar_op_basic.cc _scatter_plus_scalar:
    scalar add that only writes stored (nonzero) elements of a sparse
    input. Dense tensors have every element stored, so this is + scalar;
    the sparse-storage form lives on ndarray.sparse."""
    return data + jnp.asarray(scalar, data.dtype)


@register(name="_scatter_minus_scalar")
def _scatter_minus_scalar(data, *, scalar):
    return data - jnp.asarray(scalar, data.dtype)


@register(name="_scatter_elemwise_div")
def _scatter_elemwise_div(lhs, rhs):
    return lhs / rhs


def _logical(name, fn):
    @register(name=name, nondiff=True)
    def _op(lhs, rhs, _f=fn):
        return _f(lhs != 0, rhs != 0).astype(lhs.dtype)

    @register(name=name + "_scalar", nondiff=True)
    def _ops(data, *, scalar, _f=fn):
        return _f(data != 0, bool(scalar)).astype(data.dtype)


_logical("_logical_and", jnp.logical_and)
_logical("_logical_or", jnp.logical_or)
_logical("_logical_xor", jnp.logical_xor)
