"""Tensor operators: elemwise, broadcast, reductions, matrix, indexing, ordering.

Reference: src/operator/tensor/ (33,782 LoC: elemwise_binary_broadcast_op*,
broadcast_reduce_op*, dot, matrix_op, indexing_op.h, ordering.cc, init_op,
control_flow_op.cc `where`). Each op here is ONE pure jax function registered
with ops.registry; gradients come from jax.vjp, so the reference's hand-written
`_backward_*` kernels have no analog. MXNet numeric quirks that matter for
test parity are kept (comparison ops return values in the input dtype;
argsort/topk default to float32 indices).
"""
from __future__ import annotations

import functools
import math as _math

import numpy as _np

from ..base import MXNetError, dtype_np
from .registry import register

import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------------------------------------
# broadcast binary (reference src/operator/tensor/elemwise_binary_broadcast_op_basic.cc)
# --------------------------------------------------------------------------

def _binop(name, fn, aliases=()):
    register(name=name, aliases=aliases)(lambda lhs, rhs, _f=fn: _f(lhs, rhs))


_binop("broadcast_add", jnp.add, aliases=("elemwise_add", "_plus", "_add"))
_binop("broadcast_sub", jnp.subtract, aliases=("elemwise_sub", "_minus", "_sub"))
_binop("broadcast_mul", jnp.multiply, aliases=("elemwise_mul",))
_binop("broadcast_div", jnp.divide, aliases=("elemwise_div",))
_binop("broadcast_mod", jnp.mod, aliases=("_mod",))
_binop("broadcast_power", jnp.power, aliases=("_power", "pow"))
_binop("broadcast_maximum", jnp.maximum, aliases=("_maximum", "maximum"))
_binop("broadcast_minimum", jnp.minimum, aliases=("_minimum", "minimum"))
_binop("broadcast_hypot", jnp.hypot, aliases=("_hypot",))
_binop("arctan2", jnp.arctan2, aliases=("_arctan2",))


def _cmp(name, fn):
    @register(name="broadcast_" + name, aliases=("_" + name,), nondiff=True)
    def _op(lhs, rhs, _f=fn):
        return _f(lhs, rhs).astype(lhs.dtype)

    @register(name=f"_{name}_scalar", nondiff=True)
    def _ops(data, *, scalar, _f=fn):
        return _f(data, jnp.asarray(scalar, data.dtype)).astype(data.dtype)


_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("greater", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("lesser", jnp.less)
_cmp("lesser_equal", jnp.less_equal)

_binop("broadcast_logical_and", lambda a, b: (jnp.logical_and(a != 0, b != 0)).astype(a.dtype))
_binop("broadcast_logical_or", lambda a, b: (jnp.logical_or(a != 0, b != 0)).astype(a.dtype))
_binop("broadcast_logical_xor", lambda a, b: (jnp.logical_xor(a != 0, b != 0)).astype(a.dtype))


@register(nondiff=True)
def logical_not(data):
    return (data == 0).astype(data.dtype)


# scalar arithmetic (reference src/operator/tensor/elemwise_binary_scalar_op_basic.cc)
def _scalar_ops():
    def cvt(data, scalar):
        return jnp.asarray(scalar, data.dtype if jnp.issubdtype(data.dtype, jnp.floating) or
                           isinstance(scalar, int) else data.dtype)

    pairs = {
        "add": lambda x, s: x + s,
        "sub": lambda x, s: x - s,
        "mul": lambda x, s: x * s,
        "div": lambda x, s: x / s,
        "mod": lambda x, s: jnp.mod(x, s),
        "power": lambda x, s: jnp.power(x, s),
        "maximum": lambda x, s: jnp.maximum(x, s),
        "minimum": lambda x, s: jnp.minimum(x, s),
    }
    for n, f in pairs.items():
        register(name=f"_{n}_scalar", aliases=(f"_plus_scalar",) if n == "add" else ())(
            lambda data, *, scalar, _f=f: _f(data, jnp.asarray(scalar).astype(data.dtype)))
        register(name=f"_r{n}_scalar")(
            lambda data, *, scalar, _f=f: _f(jnp.asarray(scalar).astype(data.dtype), data))


_scalar_ops()


# --------------------------------------------------------------------------
# elemwise unary (reference src/operator/tensor/elemwise_unary_op_basic.cc + _trig etc.)
# --------------------------------------------------------------------------

def _unary(name, fn, aliases=(), nondiff=False):
    register(name=name, aliases=aliases, nondiff=nondiff)(lambda data, _f=fn: _f(data))


_unary("negative", jnp.negative, aliases=("_np_negative",))
_unary("abs", jnp.abs)
_unary("sign", jnp.sign, nondiff=True)
_unary("round", jnp.round, nondiff=True)
_unary("rint", jnp.rint, nondiff=True)
_unary("ceil", jnp.ceil, nondiff=True)
_unary("floor", jnp.floor, nondiff=True)
_unary("trunc", jnp.trunc, nondiff=True)
_unary("fix", jnp.trunc, nondiff=True)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log10", jnp.log10)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("square", jnp.square)
_unary("reciprocal", jnp.reciprocal)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("erf", lambda x: jax.scipy.special.erf(x))
_unary("erfinv", lambda x: jax.scipy.special.erfinv(x))
_unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_unary("gammaln", lambda x: jax.scipy.special.gammaln(x))
_unary("digamma", lambda x: jax.scipy.special.digamma(x))
_unary("relu", lambda x: jnp.maximum(x, 0))
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", lambda x: x / (1 + jnp.abs(x)))
_unary("identity", lambda x: x, aliases=("_copy",))
_unary("isnan", lambda x: jnp.isnan(x).astype(jnp.bool_), nondiff=True)
_unary("isinf", lambda x: jnp.isinf(x).astype(jnp.bool_), nondiff=True)
_unary("isfinite", lambda x: jnp.isfinite(x).astype(jnp.bool_), nondiff=True)
_unary("logical_not_bool", lambda x: jnp.logical_not(x), nondiff=True)


@register(name="BlockGrad", aliases=("stop_gradient",))
def block_grad(data):
    """Reference src/operator/tensor/elemwise_unary_op_basic.cc BlockGrad."""
    return lax.stop_gradient(data)


@register(name="make_loss", aliases=("MakeLoss",))
def make_loss(data, *, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Reference src/operator/make_loss.cc — identity forward; the backward
    injects grad_scale (normalized by batch size or by the count of
    elements above valid_thresh), applied multiplicatively to the head
    gradient so terminal use (head grad 1) matches the reference."""
    gs = float(grad_scale)

    @jax.custom_vjp
    def _ml(x):
        return x

    def fwd(x):
        return x, x

    def bwd(x, g):
        scale = gs
        if normalization == "batch":
            scale = gs / x.shape[0]
        elif normalization == "valid":
            nvalid = jnp.maximum(jnp.sum((x > valid_thresh).astype(
                jnp.float32)), 1.0)
            return (g * (gs / nvalid),)
        return (g * scale,)

    _ml.defvjp(fwd, bwd)
    return _ml(data)


@register()
def cast(data, *, dtype):
    """Reference src/operator/tensor/elemwise_unary_op_basic.cc Cast."""
    return data.astype(dtype_np(dtype))


Cast = cast


@register(name="amp_cast")
def amp_cast(data, *, dtype):
    """Reference src/operator/tensor/amp_cast.cc — AMP-inserted cast that only
    moves between float types."""
    return data.astype(dtype_np(dtype))


@register(name="amp_multicast", nondiff=False)
def amp_multicast(*data, num_outputs):
    """Cast all inputs to the widest float dtype present (reference amp_cast.cc)."""
    widest = jnp.result_type(*[d.dtype for d in data])
    return tuple(d.astype(widest) for d in data)


@register(name="clip")
def clip(data, *, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


# --------------------------------------------------------------------------
# reductions (reference src/operator/tensor/broadcast_reduce_op_value.cc)
# --------------------------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(name, fn, aliases=(), nondiff=False):
    @register(name=name, aliases=aliases, nondiff=nondiff)
    def _op(data, *, axis=None, keepdims=False, exclude=False, _f=fn):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            axt = (ax,) if isinstance(ax, int) else ax
            ax = tuple(i for i in range(data.ndim) if i not in
                       tuple(a % data.ndim for a in axt))
        return _f(data, axis=ax, keepdims=keepdims)


_reduce("sum", jnp.sum, aliases=("sum_axis",))
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max, aliases=("max_axis",))
_reduce("min", jnp.min, aliases=("min_axis",))


@register()
def norm(data, *, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@register(nondiff=True)
def argmax(data, *, axis=None, keepdims=False):
    if _argext_needs_split(data.shape, axis):
        return _flat_argext(data, jnp.argmax, jnp.max, keepdims, axis)
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register(nondiff=True)
def argmin(data, *, axis=None, keepdims=False):
    if _argext_needs_split(data.shape, axis):
        return _flat_argext(data, jnp.argmin, jnp.min, keepdims, axis)
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


def _argext_needs_split(shape, axis):
    """jnp.arg{max,min} positions are int32 under default jax config —
    a reduction spanning >=2^31 elements silently wraps negative
    (reference large-tensor nightly class of bug). Takes the static
    shape tuple, not the array, so the branch on it in argmax/argmin is
    visibly trace-safe (mxlint TS02)."""
    if axis is None:
        size = 1
        for d in shape:
            size *= d
        return size >= 2**31
    return shape[axis % len(shape)] >= 2**31


def _flat_argext(data, arg_fn, ext_fn, keepdims, axis=None):
    """Two-stage arg-extremum whose per-stage index fits int32; the
    position along the reduced axis is recombined in float32 (the op's
    MXNet-convention output dtype — exact whenever the position is
    f32-representable). Works for axis=None (flat) and for a named axis
    of any rank (the reduced axis moves last, leading dims batch). The
    non-divisible tail is reduced separately rather than padded: a pad
    would copy the whole >=2^31-element buffer (and need a dtype-aware
    fill that bool lacks); slices fuse into the reductions under jit."""
    if axis is None:
        rows = data.reshape(1, -1)

        def restore(o):
            return o.reshape((1,) * data.ndim) if keepdims \
                else o.reshape(())
    else:
        ax = axis % data.ndim
        moved = jnp.moveaxis(data, ax, -1)
        lead = moved.shape[:-1]
        rows = moved.reshape((-1, moved.shape[-1]))

        def restore(o):
            o = o.reshape(lead)
            return jnp.expand_dims(o, ax) if keepdims else o

    n = rows.shape[1]
    inner = 1 << 22
    if n < inner:           # directly testable small case; the >=2^31
        return restore(arg_fn(rows, axis=1).astype(jnp.float32))
    rem = n % inner
    main = rows[:, :n - rem].reshape(rows.shape[0], -1, inner)
    blk_ext = ext_fn(main, axis=2)                       # (M, k)
    outer = arg_fn(blk_ext, axis=1)                      # (M,)
    sel = jnp.take_along_axis(main, outer[:, None, None], axis=1)[:, 0]
    inner_idx = arg_fn(sel, axis=1)                      # (M,)
    best_val = jnp.take_along_axis(blk_ext, outer[:, None], axis=1)[:, 0]
    best = outer.astype(jnp.float32) * inner + inner_idx.astype(jnp.float32)
    if rem:
        tail = rows[:, n - rem:]
        t_val = ext_fn(tail, axis=1)
        t_idx = arg_fn(tail, axis=1).astype(jnp.float32) + float(n - rem)
        # strict comparison: ties resolve to the EARLIER (main) position,
        # matching numpy's first-occurrence rule
        better = t_val > best_val if ext_fn is jnp.max else t_val < best_val
        best = jnp.where(better, t_idx, best)
    return restore(best)


@register(nondiff=True)
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# --------------------------------------------------------------------------
# dot / linalg (reference src/operator/tensor/dot.cc, la_op.cc)
# --------------------------------------------------------------------------

@register()
def dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    """Reference src/operator/tensor/dot.cc. nD·mD contracts last axis of lhs
    with first axis of rhs (MXNet semantics, not numpy matmul)."""
    a = lhs.T if transpose_a and lhs.ndim == 2 else (
        jnp.transpose(lhs) if transpose_a else lhs)
    b = rhs.T if transpose_b and rhs.ndim == 2 else (
        jnp.transpose(rhs) if transpose_b else rhs)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register()
def batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    """Reference src/operator/tensor/dot.cc batch_dot: (B, m, k)x(B, k, n)."""
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register(name="linalg_gemm2")
def linalg_gemm2(a, b, *, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    x = jnp.swapaxes(a, -1, -2) if transpose_a else a
    y = jnp.swapaxes(b, -1, -2) if transpose_b else b
    return alpha * jnp.matmul(x, y)


@register(name="linalg_gemm")
def linalg_gemm(a, b, c, *, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    x = jnp.swapaxes(a, -1, -2) if transpose_a else a
    y = jnp.swapaxes(b, -1, -2) if transpose_b else b
    return alpha * jnp.matmul(x, y) + beta * c


@register(name="linalg_potrf")
def linalg_potrf(a):
    return jnp.linalg.cholesky(a)


@register(name="linalg_syrk")
def linalg_syrk(a, *, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register(name="linalg_trsm")
def linalg_trsm(a, b, *, transpose=False, rightside=False, lower=True, alpha=1.0):
    import jax.scipy.linalg as jsl
    aa = jnp.swapaxes(a, -1, -2) if transpose else a
    if rightside:
        x = jsl.solve_triangular(jnp.swapaxes(aa, -1, -2),
                                 jnp.swapaxes(alpha * b, -1, -2), lower=not lower)
        return jnp.swapaxes(x, -1, -2)
    return jsl.solve_triangular(aa, alpha * b, lower=lower)


@register(name="linalg_sumlogdiag")
def linalg_sumlogdiag(a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register(name="linalg_det")
def linalg_det(a):
    return jnp.linalg.det(a)


@register(name="linalg_inverse")
def linalg_inverse(a):
    return jnp.linalg.inv(a)


# --------------------------------------------------------------------------
# shape manipulation (reference src/operator/tensor/matrix_op.cc)
# --------------------------------------------------------------------------

def _mx_reshape_shape(in_shape, spec, reverse=False):
    """Full MXNet reshape spec: 0 copy-dim, -1 infer, -2 copy-rest,
    -3 merge-two, -4 split (reference matrix_op-inl.h InferReshapeShape)."""
    in_shape = list(in_shape)
    if reverse:
        out = _mx_reshape_shape(in_shape[::-1], list(spec)[::-1], False)
        return out[::-1]
    out, i = [], 0
    spec = list(spec)
    j = 0
    while j < len(spec):
        s = spec[j]
        if s == 0:
            out.append(in_shape[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1 if i < len(in_shape) else 0
        elif s == -2:
            out.extend(in_shape[i:]); i = len(in_shape)
        elif s == -3:
            out.append(in_shape[i] * in_shape[i + 1]); i += 2
        elif s == -4:
            d1, d2 = spec[j + 1], spec[j + 2]
            cur = in_shape[i]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(int(s)); i += 1 if i < len(in_shape) else 0
        j += 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in in_shape:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


@register(name="reshape", aliases=("Reshape",))
def reshape(data, *, shape, reverse=False):
    return jnp.reshape(data, _mx_reshape_shape(data.shape, shape, reverse))


@register(name="transpose")
def transpose(data, *, axes=None):
    if axes is not None and len(axes) == 0:
        axes = None
    return jnp.transpose(data, axes)


@register(name="swapaxes", aliases=("SwapAxis",))
def swapaxes(data, *, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register(name="expand_dims")
def expand_dims(data, *, axis):
    return jnp.expand_dims(data, axis)


@register(name="squeeze")
def squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis if axis is None else _norm_axis(axis))


@register(name="flatten", aliases=("Flatten",))
def flatten(data):
    """Reference src/operator/tensor/matrix_op.cc Flatten: (d0, rest...)->(d0, prod).
    Explicit tail product: -1 inference divides by d0, which breaks on
    0-size batches."""
    return jnp.reshape(data, (data.shape[0], _math.prod(data.shape[1:])))


@register(name="broadcast_to")
def broadcast_to(data, *, shape):
    tgt = tuple(d if s == 0 else s for s, d in zip(shape, data.shape)) \
        if len(shape) == data.ndim else tuple(shape)
    return jnp.broadcast_to(data, tgt)


@register(name="broadcast_like")
def broadcast_like(data, like):
    return jnp.broadcast_to(data, like.shape)


@register(name="broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, *, axis, size):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register(name="zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register(name="ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register(name="shape_array", nondiff=True)
def shape_array(data):
    return jnp.asarray(data.shape, jnp.int64 if False else jnp.int32)


@register(name="size_array", nondiff=True)
def size_array(data):
    return jnp.asarray([data.size], jnp.int32)


@register(name="tile")
def tile(data, *, reps):
    return jnp.tile(data, tuple(reps))


@register(name="repeat")
def repeat(data, *, repeats, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register(name="reverse", aliases=("flip",))
def reverse(data, *, axis):
    return jnp.flip(data, _norm_axis(axis))


@register(name="diag")
def diag(data, *, k=0, axis1=0, axis2=1):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)


@register(name="depth_to_space")
def depth_to_space(data, *, block_size):
    b, c, h, w = data.shape
    bs = block_size
    x = jnp.reshape(data, (b, bs, bs, c // (bs * bs), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (b, c // (bs * bs), h * bs, w * bs))


@register(name="space_to_depth")
def space_to_depth(data, *, block_size):
    b, c, h, w = data.shape
    bs = block_size
    x = jnp.reshape(data, (b, c, h // bs, bs, w // bs, bs))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (b, c * bs * bs, h // bs, w // bs))


@register(name="slice", aliases=("crop",))
def slice_op(data, *, begin, end, step=None):
    """Reference src/operator/tensor/matrix_op.cc slice."""
    nd_ = len(begin)
    idx = []
    for i in range(nd_):
        b = begin[i]
        e = end[i]
        s = (step[i] if step is not None and i < len(step) and step[i] is not None else 1)
        idx.append(slice(b, e, s))
    return data[tuple(idx)]


@register(name="slice_axis")
def slice_axis(data, *, axis, begin, end):
    axis = axis % data.ndim
    if end is None:
        end = data.shape[axis]
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register(name="slice_like")
def slice_like(data, like, *, axes=()):
    axes = tuple(axes) if axes else tuple(range(min(data.ndim, like.ndim)))
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, like.shape[a])
    return data[tuple(idx)]


@register(name="_getitem_static")
def _getitem_static(data, *, key):
    k = _thaw_index(key)
    if isinstance(k, int) and k >= 2**31:
        # jnp basic indexing materializes the index as an int32 constant,
        # which overflows past 2^31 (large-tensor audit); lax.slice
        # carries start indices as static 64-bit attributes
        return lax.squeeze(lax.slice_in_dim(data, k, k + 1, axis=0), (0,))
    return data[k]


@register(name="_index_axis0")
def _index_axis0(data, idx):
    """x[i] for a python-int i, with the index as an OPERAND: one compiled
    executable serves every i (x[i] as a static key would compile per
    distinct index — pathological for Dataset[i] loops)."""
    return jnp.take(data, idx, axis=0)


def _thaw_index(key):
    if isinstance(key, tuple) and len(key) and key[0] == "slice":
        return slice(key[1], key[2], key[3])
    if isinstance(key, tuple):
        return tuple(_thaw_index(k) for k in key)
    return key


@register(name="concat", aliases=("Concat",))
def concat(*data, dim=1, num_args=None):
    return jnp.concatenate(data, axis=dim)


@register(name="stack")
def stack(*data, axis=0, num_args=None):
    return jnp.stack(data, axis=axis)


@register(name="split", aliases=("SliceChannel", "slice_channel"))
def split(data, *, num_outputs, axis=1, squeeze_axis=False):
    """Reference src/operator/slice_channel.cc."""
    outs = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs) if num_outputs > 1 else outs[0]


@register(name="split_v2")
def split_v2(data, *, indices_or_sections, axis=0, squeeze_axis=False):
    ios = indices_or_sections
    outs = jnp.split(data, list(ios) if isinstance(ios, (tuple, list)) else ios, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs)


@register(name="where")
def where(condition, x, y):
    """Reference src/operator/tensor/control_flow_op.cc."""
    return jnp.where(condition != 0 if condition.dtype != jnp.bool_ else condition, x, y)


@register(name="pad", aliases=("Pad",))
def pad(data, *, mode="constant", pad_width=(), constant_value=0.0):
    """Reference src/operator/pad.cc. pad_width is the flat MXNet 2*ndim tuple."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(data.ndim)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


# --------------------------------------------------------------------------
# indexing (reference src/operator/tensor/indexing_op.h)
# --------------------------------------------------------------------------

@register(name="take")
def take(a, indices, *, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    return jnp.take(a, idx, axis=axis,
                    mode="clip" if mode == "clip" else "wrap")


@register(name="batch_take")
def batch_take(a, indices):
    idx = indices.astype(jnp.int32)
    return a[jnp.arange(a.shape[0]), idx]


@register(name="pick")
def pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis=axis), axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register(name="one_hot", nondiff=True)
def one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    idx = indices.astype(jnp.int32)
    oh = jax.nn.one_hot(idx, depth)
    return (oh * (on_value - off_value) + off_value).astype(dtype_np(dtype))


@register(name="gather_nd")
def gather_nd(data, indices):
    """Reference indexing_op.h GatherNDForward: indices (M, ...) leading."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register(name="scatter_nd")
def scatter_nd(data, indices, *, shape):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register(name="_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, *, shape):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)


@register(name="Embedding", aliases=("embedding",))
def embedding(data, weight, *, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    """Reference src/operator/tensor/indexing_op.cc Embedding."""
    return weight[data.astype(jnp.int32)]


@register(name="boolean_mask", eager_only=True)
def boolean_mask(data, index, *, axis=0):
    """Reference src/operator/contrib/boolean_mask.cc. Dynamic output shape —
    eager-only (XLA needs static shapes; inside jit use `where`)."""
    mask = _np.asarray(index) != 0
    return jnp.compress(mask, data, axis=axis)


# --------------------------------------------------------------------------
# ordering (reference src/operator/tensor/ordering_op.cc)
# --------------------------------------------------------------------------

@register(name="sort")
def sort(data, *, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register(name="argsort", nondiff=True)
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype_np(dtype))


@register(name="topk")
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Reference src/operator/tensor/ordering_op.cc TopK. On TPU the descending
    case lowers to lax.top_k (sorted on the MXU-adjacent VPU)."""
    if axis is None:
        data = jnp.reshape(data, (-1,))
        axis = 0
    axis = axis % data.ndim
    moved = jnp.moveaxis(data, axis, -1)
    if is_ascend:
        vals, idxs = lax.top_k(-moved, k)
        vals = -vals
    else:
        vals, idxs = lax.top_k(moved, k)
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(dtype_np(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idxs
    if ret_typ == "both":
        return (vals, idxs)
    # mask
    oh = jnp.sum(jax.nn.one_hot(jnp.moveaxis(idxs, axis, -1).astype(jnp.int32),
                                data.shape[axis]), axis=-2)
    return jnp.moveaxis(oh, -1, axis).astype(data.dtype)


# --------------------------------------------------------------------------
# init ops (reference src/operator/tensor/init_op.cc)
# --------------------------------------------------------------------------

@register(name="_zeros", nondiff=True)
def _zeros(*, shape, dtype="float32"):
    return jnp.zeros(tuple(shape), dtype_np(dtype))


@register(name="_ones", nondiff=True)
def _ones(*, shape, dtype="float32"):
    return jnp.ones(tuple(shape), dtype_np(dtype))


@register(name="_full", nondiff=True)
def _full(*, shape, value, dtype="float32"):
    return jnp.full(tuple(shape), value, dtype_np(dtype))


@register(name="_arange", nondiff=True)
def _arange(*, start, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype_np(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register(name="_eye", nondiff=True)
def _eye(*, N, M=0, k=0, dtype="float32"):
    return jnp.eye(N, M if M else None, k=k, dtype=dtype_np(dtype))


# --------------------------------------------------------------------------
# sequence ops (reference src/operator/sequence_mask.cc / _last.cc / _reverse.cc)
# --------------------------------------------------------------------------

def _seq_mask(data, sequence_length, value, axis):
    # data: axis 0 = time (axis param selects 0 or 1), sequence_length (batch,)
    T = data.shape[axis]
    batch_axis = 1 - axis
    steps = jnp.arange(T)
    mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)  # (T, B)
    if axis == 1:
        mask = mask.T
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    shape[batch_axis] = data.shape[batch_axis]
    mask = jnp.reshape(mask, shape)
    return mask


@register(name="SequenceMask", aliases=("sequence_mask",))
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    mask = _seq_mask(data, sequence_length, value, axis)
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register(name="SequenceLast", aliases=("sequence_last",))
def sequence_last(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jnp.take_along_axis(
        moved, last.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0)[0]


@register(name="SequenceReverse", aliases=("sequence_reverse",))
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(steps < lens, lens - 1 - steps, steps)  # (T, B)
    rev_idx = rev_idx.reshape((T, -1) + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, jnp.broadcast_to(rev_idx, data.shape), axis=0)


# --------------------------------------------------------------------------
# misc (L2Normalization, histogram, ravel, ...)
# --------------------------------------------------------------------------

@register(name="L2Normalization")
def l2_normalization(data, *, eps=1e-10, mode="instance"):
    """Reference src/operator/l2_normalization.cc."""
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    else:  # spatial
        ax = tuple(range(2, data.ndim))
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / nrm


@register(name="_histogram", aliases=("histogram",), nondiff=True)
def _histogram(data, *, bin_cnt=10, range=None):
    lo, hi = range if range is not None else (float(data.min()), float(data.max()))
    hist, edges = jnp.histogram(data, bins=bin_cnt, range=(lo, hi))
    return (hist.astype(jnp.int64 if False else jnp.int32), edges)


@register(name="_ravel_multi_index", nondiff=True)
def _ravel_multi_index(data, *, shape):
    idx = data.astype(jnp.int32)
    strides = _np.cumprod([1] + list(shape[::-1]))[::-1][1:]
    strides = jnp.asarray(_np.ascontiguousarray(strides), jnp.int32)
    return jnp.sum(idx * strides[:, None], axis=0).astype(jnp.float32)


@register(name="_unravel_index", nondiff=True)
def _unravel_index(data, *, shape):
    idx = data.astype(jnp.int32)
    outs = jnp.stack(jnp.unravel_index(idx, tuple(shape)), axis=0)
    return outs.astype(jnp.float32)


@register(name="smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    """Reference src/operator/tensor/elemwise_binary_scalar_op_extended.cc."""
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * jnp.square(data), absd - 0.5 / s2)


@register(name="cumsum", aliases=("_np_cumsum",))
def cumsum(a, *, axis=None, dtype=None):
    """Reference src/operator/numpy/np_cumsum.cc."""
    return jnp.cumsum(a, axis=axis,
                      dtype=dtype_np(dtype) if dtype else None)


@register(name="Crop")
def crop_op(*data, num_args=None, offset=(0, 0), h_w=(0, 0),
            center_crop=False):
    """Legacy v0 Crop (reference src/operator/crop.cc): crop data (N,C,H,W)
    to h_w (or to the second input's spatial size), at `offset` or
    centered. NOTE: lowercase `crop` stays the slice alias, as in the
    reference; num_args defaults to the number of inputs (the C API
    infers it)."""
    x = data[0]
    if num_args is None:
        num_args = len(data)
    if num_args == 2 and len(data) > 1:
        th, tw = data[1].shape[2], data[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = x.shape[2], x.shape[3]
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    return x[:, :, y0:y0 + th, x0:x0 + tw]


@register(name="IdentityAttachKLSparseReg",
          aliases=("identity_attach_kl_sparse_reg",))
def identity_attach_kl_sparse_reg(data, *, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity forward; backward ADDS the KL-sparsity penalty gradient
    on mean activations (reference
    src/operator/identity_attach_KL_sparse_reg.cc — sparse-autoencoder
    regularizer). The running-average momentum state of the reference is
    folded into the per-batch mean (stateless functional form)."""
    rho = float(sparseness_target)
    pen = float(penalty)

    @jax.custom_vjp
    def _kl(x):
        return x

    def fwd(x):
        return x, x

    def bwd(x, g):
        rho_hat = jnp.clip(jnp.mean(x, axis=0, keepdims=True), 1e-6,
                           1 - 1e-6)
        # NO 1/N factor: the reference adds the raw penalty per element
        # (identity_attach_KL_sparse_reg-inl.h Backward)
        kl_grad = pen * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return (g + kl_grad.astype(g.dtype),)

    _kl.defvjp(fwd, bwd)
    return _kl(data)
