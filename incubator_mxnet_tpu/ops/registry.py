"""Operator registry + eager dispatch.

Reference: NNVM op registry (`NNVM_REGISTER_OP`, 338 registrations in
src/operator/) with typed attributes FInferShape/FInferType/FCompute/FGradient
(include/mxnet/op_attr_types.h), dispatched by Imperative::Invoke
(src/imperative/imperative.cc:89) through the ThreadedEngine.

TPU-native redesign: an op is ONE pure jax function (`fn(*arrays, **params)`)
— shape/dtype inference comes free from `jax.eval_shape` (no separate
FInferShape), the gradient comes free from `jax.vjp` (no hand-written
`_backward_*` ops), and the "engine" is XLA async dispatch (jax.Array data
dependencies replace the reference's var version chains). Each eager call is
routed through a cached `jax.jit` specialization keyed on (op, shapes,
dtypes, params) so steady-state eager dispatch stays on the fast path — the
moral equivalent of the reference's CachedOp op-bulking without the graph.
"""
from __future__ import annotations

import functools
import weakref

from .. import autograd
from ..base import MXNetError, Registry

__all__ = ["OpDef", "register", "get_op", "invoke", "OPS", "apply_op"]

OPS = Registry("operator")

# AMP dispatch hook (contrib/amp/amp.py): fn(op_name, arr_list, params) ->
# arr_list, applied to unwrapped jax arrays before dispatch. The reference
# instead monkey-patches every generated op wrapper (contrib/amp/amp.py:
# 48-140); here ONE choke point covers eager, hybridized, and symbolic
# execution.
AMP_HOOK = None

# Profiler dispatch hook (profiler.py): fn(op_name, callable, args) -> out,
# times eager op dispatch (the reference wraps engine-op execution,
# src/profiler/profiler.h:251).
PROFILER_HOOK = None


def _match_ct_dtypes(cts, out):
    """Cast cotangents to the primal outputs' dtypes — under AMP a bf16
    op output can receive an fp32 cotangent from a downstream fp32 op."""
    import jax.numpy as jnp

    def _one(ct, o):
        if hasattr(ct, "dtype") and hasattr(o, "dtype") and ct.dtype != o.dtype:
            return ct.astype(o.dtype)
        return ct

    if isinstance(out, (tuple, list)):
        return tuple(_one(c, o) for c, o in zip(cts, out))
    return _one(cts, out)


def _hashable(v):
    if isinstance(v, (list,)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


class OpDef:
    """One registered operator.

    fn: pure function of jax arrays (positional) + python params (keyword),
    returning one array or a tuple. `stateful=True` ops (random samplers,
    dropout) additionally take a `rng` keyword PRNG key.
    """

    def __init__(self, name, fn, aliases=(), stateful=False, nondiff=False,
                 train_aware=False, eager_only=False):
        self.name = name
        self.fn = fn
        self.aliases = aliases
        self.stateful = stateful
        self.nondiff = nondiff
        # eager_only: dynamic output shape (boolean_mask) — never jit; XLA
        # needs static shapes, so these run op-by-op with concrete inputs
        self.eager_only = eager_only
        # train_aware ops (BatchNorm, Dropout) get `training=` injected from the
        # autograd train-mode flag when the caller didn't pass it — mirrors the
        # reference's ctx.is_train threading (include/mxnet/op_attr_types.h
        # OpContext::is_train).
        self.train_aware = train_aware
        # bounded FIFO: params may embed user callables (control-flow
        # bodies) whose identity changes per call-site — an unbounded dict
        # would leak every compiled executable + captured closure
        self._jit_cache = {}
        self._jit_cache_max = 256

    def vjp_jitted(self, **params):
        """Cached jitted backward: (cts, *primals) -> input cotangents.

        Recomputes the forward inside the executable (rematerialization) so
        the whole fwd+bwd pair is compiled ONCE per (op, params, shapes) and
        reused every step — the reference's analog is the cached `_backward_*`
        op + autotuned kernel; a fresh jax.vjp per call would recompile the
        linearized program every training step.
        """
        import jax
        key = ("vjp", _hashable(params))
        f = self._jit_cache.get(key)
        if f is None:
            if self.stateful:
                def fwd(rng, *xs, _p=params):
                    return self.fn(*xs, rng=rng, **_p)
            else:
                def fwd(*xs, _p=params):
                    return self.fn(*xs, **_p)

            def bwd(cts, *primals):
                out, vjp_fn = jax.vjp(fwd, *primals)
                return vjp_fn(_match_ct_dtypes(cts, out))

            # two-tier executable cache: reports hit/disk-hit/retrace to
            # the profiler's jit tracker and AOT-persists the executable
            from .. import compile_cache as _cc
            f = _cc.cached_jit(f"op:{self.name}:vjp", bwd)
            self._cache_put(key, f)
        return f

    def _cache_put(self, key, f):
        if len(self._jit_cache) >= self._jit_cache_max:
            self._jit_cache.pop(next(iter(self._jit_cache)))
        self._jit_cache[key] = f

    def jitted(self, **params):
        """A jax.jit specialization of this op for the given params.

        Stateful ops receive the PRNG key as a traced leading argument so the
        jit cache is keyed on params only, never on key values.
        """
        key = _hashable(params)
        f = self._jit_cache.get(key)
        if f is None:
            # two-tier executable cache: every call through it reports
            # hit/disk-hit/recompile to the profiler's jit tracker, and the
            # compiled executable persists across processes when
            # MXNET_EXEC_CACHE_DIR is set
            from .. import compile_cache as _cc
            if self.stateful:
                base = self.fn

                def f_rng(rng, *arrs, _base=base, _params=params):
                    return _base(*arrs, rng=rng, **_params)

                f = _cc.cached_jit(f"op:{self.name}", f_rng)
            else:
                f = _cc.cached_jit(f"op:{self.name}",
                                   functools.partial(self.fn, **params))
            self._cache_put(key, f)
        return f

    def __call__(self, *args, **kwargs):
        return apply_op(self, *args, **kwargs)

    def __repr__(self):
        return f"<Op {self.name}>"


def register(name=None, aliases=(), stateful=False, nondiff=False, train_aware=False,
             eager_only=False):
    """Decorator: @register() on `def op_name(x, y, *, param): ...`."""

    def _do(fn):
        opname = name or fn.__name__
        op = OpDef(opname, fn, aliases=aliases, stateful=stateful, nondiff=nondiff,
                   train_aware=train_aware, eager_only=eager_only)
        OPS.register(op, name=opname, aliases=aliases)
        return op

    return _do


def get_op(name) -> OpDef:
    return OPS.get(name)


def _wrap_out(x, like=None):
    from ..ndarray import NDArray
    return NDArray(x)


def apply_op(op: OpDef, *args, out=None, **params):
    """Eager invoke: unwrap NDArrays -> run jax fn -> wrap outputs -> record tape.

    Reference call path: MXImperativeInvokeEx (src/c_api/c_api_ndarray.cc:132)
    -> Imperative::Invoke (imperative.cc:89) -> PushFCompute
    (imperative_utils.h:394) -> Engine::PushAsync. Here the whole path is one
    cached-jit call; XLA's async runtime gives the same compute/dispatch overlap.
    """
    import jax
    from ..ndarray import NDArray

    arrs = []
    nd_inputs = []
    for a in args:
        if isinstance(a, NDArray):
            nd_inputs.append(a)
            arrs.append(a._data)
        else:
            arrs.append(a)

    if AMP_HOOK is not None:
        arrs = AMP_HOOK(op.name, arrs, params)

    if op.train_aware and params.get("training") is None:
        params = dict(params)
        params["training"] = autograd.is_training()

    if op.stateful:
        from ..ndarray import random as _rnd
        rng = params.pop("rng", None)
        if rng is None:
            rng = _rnd.next_key()
        arrs = [rng] + arrs

    recording = autograd.is_recording() and not op.nondiff

    # Inside an outer trace (hybridize / pjit train step) call the raw fn:
    # nested jit would both block some vjp rules (reduce_window) and prevent
    # whole-graph fusion. Eagerly, the jit-cached specialization is the fast
    # dispatch path (reference: engine op bulking, graph_executor.cc:1288).
    import jax.core as _core
    traced = any(isinstance(a, _core.Tracer) for a in arrs)
    if traced or op.eager_only:
        if op.stateful:
            fn = lambda rng, *xs, _p=params: op.fn(*xs, rng=rng, **_p)
        else:
            fn = lambda *xs, _p=params: op.fn(*xs, **_p)
    else:
        fn = op.jitted(**params)

    bwd_info = None
    if recording and traced:
        # inside an outer trace the vjp is part of that trace; no caching issue
        out_data, _raw_vjp = jax.vjp(fn, *arrs)
        vjp_fn = lambda cts, _v=_raw_vjp, _o=out_data: \
            _v(_match_ct_dtypes(cts, _o))
    elif recording and op.eager_only:
        # dynamic-shape op: the jit-cached vjp would re-trace op.fn with
        # abstract inputs, defeating eager_only. Differentiate only arg 0
        # (data); the rest (masks/indices) stay concrete python values so
        # op.fn can inspect them, and get zero cotangents.
        rest = tuple(arrs[1:])
        out_data, _raw_vjp = jax.vjp(
            lambda d, _r=rest, _p=params: op.fn(d, *_r, **_p), arrs[0])

        def vjp_fn(cts, _v=_raw_vjp, _o=out_data, _r=rest):
            gd = _v(_match_ct_dtypes(cts, _o))
            import jax.numpy as _jnp
            return (gd[0],) + tuple(_jnp.zeros_like(r) for r in _r)
    else:
        if PROFILER_HOOK is not None and not traced:
            out_data = PROFILER_HOOK(op.name, fn, arrs)
        else:
            out_data = fn(*arrs)
        vjp_fn = None
        if recording:
            # deferred, jit-cached backward (recomputes forward in-executable)
            bwd = op.vjp_jitted(**params)
            saved = list(arrs)
            vjp_fn = lambda cts, _b=bwd, _s=saved: _b(cts, *_s)
            bwd_info = (op, dict(params), saved)

    multi = isinstance(out_data, (tuple, list))
    # Class-preserving wrap: an mxnet.numpy ndarray input propagates its
    # class through every op (the reference instead duplicates the whole op
    # surface as _np_* registrations, src/operator/numpy/).
    out_cls = type(nd_inputs[0]) if nd_inputs else NDArray
    outs = [out_cls(o) for o in (out_data if multi else (out_data,))]

    if recording:
        off = 1 if op.stateful else 0
        ndarray_positions = [i + off for i, a in enumerate(args) if isinstance(a, NDArray)]

        def node_vjp(cts):
            gin = vjp_fn(cts)
            return tuple(gin[i] for i in ndarray_positions)

        node = autograd.Node(node_vjp, nd_inputs, op.name)
        node.out_refs = [weakref.ref(o) for o in outs]
        node.out_avals = [(o.shape, o.dtype) for o in outs]
        # create_graph (higher-order) support: enough context to replay
        # this node's backward as a RECORDED op (autograd._record_bwd)
        if bwd_info is not None:
            node.bwd_info = (bwd_info[0], bwd_info[1], bwd_info[2],
                             list(ndarray_positions))
        for o in outs:
            o._ag_node = node

    if out is not None:
        tgt = out if isinstance(out, (tuple, list)) else (out,)
        for t, o in zip(tgt, outs):
            t._data = o._data
            t._ag_node = getattr(o, "_ag_node", None)
        return out
    if multi:
        return outs
    return outs[0]


def invoke(name, *args, **kwargs):
    return apply_op(get_op(name), *args, **kwargs)
