"""Optimizers.

Reference: python/mxnet/optimizer/optimizer.py (1,901 LoC): `Optimizer` base
with registry, lr/wd multipliers, `Updater` (state dict + serialization for
the kvstore server), and SGD/Signum/FTML/NAG/SGLD/Adam/AdaGrad/RMSProp/
AdaDelta/Ftrl/Adamax/Nadam/DCASGD/LBSGD — each mapping to fused update ops.

TPU-native: every update calls a registered jit-cached update op
(ops/optimizer_ops.py), so eager Trainer steps run one XLA executable per
parameter; fully-jitted train steps reuse the same op functions inline.
"""
from __future__ import annotations

import math
import pickle

import numpy as _np

from .. import nd
from ..base import MXNetError, Registry
from ..ndarray.ndarray import NDArray
from ..ops.registry import invoke

__all__ = ["Optimizer", "SGD", "Signum", "SignSGD", "FTML", "NAG", "SGLD",
           "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax",
           "Nadam", "DCASGD", "LBSGD", "AdamW", "Test", "create", "register",
           "Updater", "get_updater"]

_REG = Registry("optimizer")


def _is_row_sparse(grad):
    return getattr(grad, "stype", "default") == "row_sparse"


def _sparse_sgd_update(weight, grad, state, lr, wd, momentum, rescale,
                       clip):
    """Lazy row_sparse SGD (reference optimizer_op.cc SGDUpdateRsp): only
    rows present in the gradient are touched — weight, momentum, AND the
    fp32 master copy in multi-precision mode."""
    import jax.numpy as jnp

    idx = grad.indices._data
    mom, w32 = (state if isinstance(state, tuple) else (state, None))
    # multi-precision: compute on the fp32 master rows
    master = w32 if w32 is not None else weight
    g = grad.data._data.astype(master.dtype) * rescale
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    w_rows = master._data[idx]
    g = g + wd * w_rows
    if mom is not None:
        m_rows = momentum * mom._data[idx] - lr * g
        mom._data = mom._data.at[idx].set(m_rows)
        master._data = master._data.at[idx].add(m_rows)
    else:
        master._data = master._data.at[idx].add(-lr * g)
    if w32 is not None:
        weight._data = weight._data.at[idx].set(
            master._data[idx].astype(weight.dtype))


def _sparse_adam_update(weight, grad, mean, var, lr, beta1, beta2, eps, wd,
                        rescale, clip):
    """Lazy row_sparse Adam (reference optimizer_op.cc AdamUpdateRsp)."""
    import jax.numpy as jnp

    idx = grad.indices._data
    g = grad.data._data.astype(weight.dtype) * rescale
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    w_rows = weight._data[idx]
    g = g + wd * w_rows
    m_rows = beta1 * mean._data[idx] + (1 - beta1) * g
    v_rows = beta2 * var._data[idx] + (1 - beta2) * g * g
    mean._data = mean._data.at[idx].set(m_rows)
    var._data = var._data.at[idx].set(v_rows)
    weight._data = weight._data.at[idx].add(
        -lr * m_rows / (jnp.sqrt(v_rows) + eps))


def register(cls):
    _REG.register(cls)
    return cls


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REG.get(name)(**kwargs)


class Optimizer:
    """Base optimizer (reference optimizer.py:47)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- registry-compatible helpers ---------------------------------------
    @staticmethod
    def create_optimizer(name, **kwargs):
        return create(name, **kwargs)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        # str compare: numpy has no bfloat16 scalar type, but ml_dtypes'
        # bfloat16 stringifies to "bfloat16" — both half formats get an
        # fp32 master copy (reference optimizer.py multi_precision fp16)
        if self.multi_precision and str(weight.dtype) in ("float16",
                                                          "bfloat16"):
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def _fused_spec(self, index, weight, state):
        """Aggregation protocol: describe this param's update as ONE call of
        a registered single-tensor op so ops.optimizer_ops.fused_apply can
        bucket it. Returns (op_name, state_arrays, static_kwargs,
        dyn_kwargs) — static_kwargs key the jit cache, dyn_kwargs (lr with
        any bias correction folded in, wd) become traced vectors so
        lr_scheduler steps don't recompile. None opts out (eager-math
        optimizers, randomized updates, time-static kwargs like FTML's t).

        Called twice per step: a probe BEFORE update counts commit (must
        not raise on unseen indices) and again after, for the final lr."""
        return None

    def update_multi(self, indices, weights, grads, states):
        """Aggregated bucket update (reference optimizer.py aggregate_num
        branch of _update_impl): ONE fused dispatch when every param in the
        bucket maps to the same single-tensor op, else the per-param
        oracle. Returns the number of jit dispatches issued — the
        Trainer's trainer_dispatches_per_step counter sums these."""
        from ..ops.optimizer_ops import fused_apply
        if len(indices) > 1 and fused_apply(self, indices, weights, grads,
                                            states):
            return 1
        for i, w, g, s in zip(indices, weights, grads, states):
            self.update_multi_precision(i, w, g, s)
        return len(indices)

    def set_learning_rate(self, lr):
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _clip(self):
        return self.clip_gradient if self.clip_gradient is not None else -1.0

    def __getstate__(self):
        d = self.__dict__.copy()
        return d


@register
class SGD(Optimizer):
    """(Momentum/multi-precision) SGD → sgd_update / sgd_mom_update /
    mp_sgd_* ops (reference optimizer.py SGD, optimizer_op.cc)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if _is_row_sparse(grad):
            # lazy update: only the rows present in the sparse grad move
            # (reference optimizer_op.cc SGDUpdateRsp / sgd_mom row_sparse)
            _sparse_sgd_update(weight, grad, state, lr, wd, self.momentum,
                               self.rescale_grad, self._clip())
            return
        if isinstance(state, tuple):  # multi-precision
            mom, w32 = state
            if mom is not None:
                w_new, m_new, w32_new = invoke(
                    "mp_sgd_mom_update", weight, grad, mom, w32, lr=lr,
                    momentum=self.momentum, wd=wd, rescale_grad=self.rescale_grad,
                    clip_gradient=self._clip())
                mom._data = m_new._data
            else:
                w_new, w32_new = invoke("mp_sgd_update", weight, grad, w32,
                                        lr=lr, wd=wd,
                                        rescale_grad=self.rescale_grad,
                                        clip_gradient=self._clip())
            weight._data = w_new._data
            w32._data = w32_new._data
        elif state is not None:
            w_new, m_new = invoke("sgd_mom_update", weight, grad, state, lr=lr,
                                  momentum=self.momentum, wd=wd,
                                  rescale_grad=self.rescale_grad,
                                  clip_gradient=self._clip())
            weight._data = w_new._data
            state._data = m_new._data
        else:
            w_new = invoke("sgd_update", weight, grad, lr=lr, wd=wd,
                           rescale_grad=self.rescale_grad,
                           clip_gradient=self._clip())
            weight._data = w_new._data

    update_multi_precision = update

    def _fused_spec(self, index, weight, state):
        dyn = {"lr": self._get_lr(index), "wd": self._get_wd(index)}
        if isinstance(state, tuple):  # multi-precision (mom-or-None, w32)
            mom, w32 = state
            if mom is not None:
                return ("mp_sgd_mom_update", [mom, w32],
                        {"momentum": self.momentum,
                         "clip_gradient": self._clip()}, dyn)
            return ("mp_sgd_update", [w32],
                    {"clip_gradient": self._clip()}, dyn)
        if state is not None:
            return ("sgd_mom_update", [state],
                    {"momentum": self.momentum,
                     "clip_gradient": self._clip()}, dyn)
        return ("sgd_update", [], {"clip_gradient": self._clip()}, dyn)


@register
class SignSGD(Optimizer):
    def update(self, index, weight, grad, state):
        self._update_count(index)
        w = invoke("signsgd_update", weight, grad, lr=self._get_lr(index),
                   wd=self._get_wd(index), rescale_grad=self.rescale_grad,
                   clip_gradient=self._clip())
        weight._data = w._data

    def _fused_spec(self, index, weight, state):
        return ("signsgd_update", [], {"clip_gradient": self._clip()},
                {"lr": self._get_lr(index), "wd": self._get_wd(index)})


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        w, m = invoke("signum_update", weight, grad, state,
                      lr=self._get_lr(index), momentum=self.momentum,
                      wd=self._get_wd(index), rescale_grad=self.rescale_grad,
                      clip_gradient=self._clip(), wd_lh=self.wd_lh)
        weight._data, state._data = w._data, m._data

    def _fused_spec(self, index, weight, state):
        return ("signum_update", [state],
                {"momentum": self.momentum, "wd_lh": self.wd_lh,
                 "clip_gradient": self._clip()},
                {"lr": self._get_lr(index), "wd": self._get_wd(index)})


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        d, v, z = state
        w, d2, v2, z2 = invoke("ftml_update", weight, grad, d, v, z,
                               lr=self._get_lr(index), beta1=self.beta1,
                               beta2=self.beta2, epsilon=self.epsilon,
                               wd=self._get_wd(index),
                               rescale_grad=self.rescale_grad,
                               clip_grad=self._clip(), t=t)
        weight._data, d._data, v._data, z._data = w._data, d2._data, v2._data, z2._data


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        if state is None:
            SGD.update(self, index, weight, grad, None)  # plain sgd
            return
        w, m = invoke("nag_mom_update", weight, grad, state,
                      lr=self._get_lr(index), momentum=self.momentum,
                      wd=self._get_wd(index), rescale_grad=self.rescale_grad,
                      clip_gradient=self._clip())
        weight._data, state._data = w._data, m._data

    def _fused_spec(self, index, weight, state):
        dyn = {"lr": self._get_lr(index), "wd": self._get_wd(index)}
        if state is None:  # momentum==0 degenerates to plain sgd
            return ("sgd_update", [], {"clip_gradient": self._clip()}, dyn)
        return ("nag_mom_update", [state],
                {"momentum": self.momentum,
                 "clip_gradient": self._clip()}, dyn)


@register
class SGLD(Optimizer):
    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype=str(weight.dtype))
        weight._data = (weight - lr / 2 * (g + wd * weight) + noise)._data


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        lr *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        if _is_row_sparse(grad):
            # lazy adam: moments + weight move only on touched rows
            # (reference AdamUpdateRsp, optimizer_op.cc)
            _sparse_adam_update(weight, grad, mean, var, lr, self.beta1,
                                self.beta2, self.epsilon,
                                self._get_wd(index), self.rescale_grad,
                                self._clip())
            return
        w, m, v = invoke("adam_update", weight, grad, mean, var, lr=lr,
                         beta1=self.beta1, beta2=self.beta2,
                         epsilon=self.epsilon, wd=self._get_wd(index),
                         rescale_grad=self.rescale_grad,
                         clip_gradient=self._clip())
        weight._data, mean._data, var._data = w._data, m._data, v._data

    def update_multi_precision(self, index, weight, grad, state):
        # mp state layout from create_state_multi_precision:
        # ((mean32, var32), w32); plain state is just (mean, var)
        if not (isinstance(state, tuple) and len(state) == 2
                and isinstance(state[0], tuple)):
            return self.update(index, weight, grad, state)
        (mean, var), w32 = state
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        lr *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        if _is_row_sparse(grad):
            # lazy rows on the fp32 master, then refresh the working copy
            _sparse_adam_update(w32, grad, mean, var, lr, self.beta1,
                                self.beta2, self.epsilon,
                                self._get_wd(index), self.rescale_grad,
                                self._clip())
            idx = grad.indices._data
            weight._data = weight._data.at[idx].set(
                w32._data[idx].astype(weight.dtype))
            return
        w, m, v, w32n = invoke("mp_adam_update", weight, grad, mean, var,
                               w32, lr=lr, beta1=self.beta1,
                               beta2=self.beta2, epsilon=self.epsilon,
                               wd=self._get_wd(index),
                               rescale_grad=self.rescale_grad,
                               clip_gradient=self._clip())
        weight._data, mean._data, var._data, w32._data = \
            w._data, m._data, v._data, w32n._data

    def _fused_spec(self, index, weight, state):
        # the probe runs before counts commit — .get keeps it from raising
        # on unseen indices; its lr is discarded, the post-commit call sees
        # the real t
        t = self._index_update_count.get(index, self.begin_num_update + 1)
        lr = self._get_lr(index)
        lr *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        dyn = {"lr": lr, "wd": self._get_wd(index)}
        static = {"beta1": self.beta1, "beta2": self.beta2,
                  "epsilon": self.epsilon, "clip_gradient": self._clip()}
        if isinstance(state[0], tuple):  # multi-precision
            (mean, var), w32 = state
            return ("mp_adam_update", [mean, var, w32], static, dyn)
        mean, var = state
        return ("adam_update", [mean, var], static, dyn)


@register
class AdamW(Optimizer):
    """Decoupled weight decay (reference src/operator/contrib/adamw.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon, self.eta = beta1, beta2, epsilon, eta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        mean, var = state
        w, m, v = invoke("adamw_update", weight, grad, mean, var,
                         lr=self._get_lr(index), beta1=self.beta1,
                         beta2=self.beta2, epsilon=self.epsilon,
                         wd=self._get_wd(index), eta=self.eta,
                         rescale_grad=self.rescale_grad,
                         clip_gradient=self._clip())
        weight._data, mean._data, var._data = w._data, m._data, v._data

    def _fused_spec(self, index, weight, state):
        mean, var = state
        return ("adamw_update", [mean, var],
                {"beta1": self.beta1, "beta2": self.beta2,
                 "epsilon": self.epsilon, "eta": self.eta,
                 "clip_gradient": self._clip()},
                {"lr": self._get_lr(index), "wd": self._get_wd(index)})


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        g = g + wd * weight
        state._data = (state + nd.square(g))._data
        weight._data = (weight - lr * g / (nd.sqrt(state) + self.float_stable_eps))._data


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights if clip_weights is not None else -1.0

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, dtype=weight.dtype),
                    nd.zeros(weight.shape, dtype=weight.dtype),
                    nd.zeros(weight.shape, dtype=weight.dtype))
        return nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self.centered:
            n, g_s, delta = state
            w, n2, g2, d2 = invoke("rmspropalex_update", weight, grad, n, g_s,
                                   delta, lr=lr, gamma1=self.gamma1,
                                   gamma2=self.gamma2, epsilon=self.epsilon,
                                   wd=wd, rescale_grad=self.rescale_grad,
                                   clip_gradient=self._clip(),
                                   clip_weights=self.clip_weights)
            weight._data, n._data, g_s._data, delta._data = \
                w._data, n2._data, g2._data, d2._data
        else:
            w, n2 = invoke("rmsprop_update", weight, grad, state, lr=lr,
                           gamma1=self.gamma1, epsilon=self.epsilon, wd=wd,
                           rescale_grad=self.rescale_grad,
                           clip_gradient=self._clip(),
                           clip_weights=self.clip_weights)
            weight._data, state._data = w._data, n2._data

    def _fused_spec(self, index, weight, state):
        dyn = {"lr": self._get_lr(index), "wd": self._get_wd(index)}
        if self.centered:
            n, g_s, delta = state
            return ("rmspropalex_update", [n, g_s, delta],
                    {"gamma1": self.gamma1, "gamma2": self.gamma2,
                     "epsilon": self.epsilon,
                     "clip_gradient": self._clip(),
                     "clip_weights": self.clip_weights}, dyn)
        return ("rmsprop_update", [state],
                {"gamma1": self.gamma1, "epsilon": self.epsilon,
                 "clip_gradient": self._clip(),
                 "clip_weights": self.clip_weights}, dyn)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        g = g + wd * weight
        acc_g, acc_delta = state
        acc_g._data = (self.rho * acc_g + (1 - self.rho) * nd.square(g))._data
        delta = nd.sqrt(acc_delta + self.epsilon) / nd.sqrt(acc_g + self.epsilon) * g
        acc_delta._data = (self.rho * acc_delta + (1 - self.rho) * nd.square(delta))._data
        weight._data = (weight - delta)._data


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        w, z2, n2 = invoke("ftrl_update", weight, grad, z, n,
                           lr=self._get_lr(index), lamda1=self.lamda1,
                           beta=self.beta, wd=self._get_wd(index),
                           rescale_grad=self.rescale_grad,
                           clip_gradient=self._clip())
        weight._data, z._data, n._data = w._data, z2._data, n2._data

    def _fused_spec(self, index, weight, state):
        z, n = state
        return ("ftrl_update", [z, n],
                {"lamda1": self.lamda1, "beta": self.beta,
                 "clip_gradient": self._clip()},
                {"lr": self._get_lr(index), "wd": self._get_wd(index)})


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        g = g + wd * weight
        m, u = state
        m._data = (self.beta1 * m + (1 - self.beta1) * g)._data
        u._data = nd.maximum(self.beta2 * u, nd.abs(g))._data
        weight._data = (weight - lr * m / (u + 1e-8))._data


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype),
                nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        g = g + wd * weight
        m_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        m_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= m_t
        m_schedule_next = self.m_schedule * m_t_1
        m, v = state
        m._data = (self.beta1 * m + (1.0 - self.beta1) * g)._data
        v._data = (self.beta2 * v + (1.0 - self.beta2) * nd.square(g))._data
        g_prime = g / (1.0 - self.m_schedule)
        m_prime = m / (1.0 - m_schedule_next)
        v_prime = v / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - m_t) * g_prime + m_t_1 * m_prime
        weight._data = (weight - lr * m_bar / (nd.sqrt(v_prime) + self.epsilon))._data


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype) if self.momentum else None,
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        mom, prev = state
        adj = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is not None:
            mom._data = (self.momentum * mom - lr * adj)._data
            step = mom
        else:
            step = -lr * adj
        prev._data = weight._data
        weight._data = (weight + step)._data


@register
class LBSGD(SGD):
    """Large-batch SGD w/ LARS-style scaling (reference optimizer.py LBSGD);
    approximated by layer-wise adaptive rate on top of SGD momentum."""

    def __init__(self, momentum=0.0, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, **kwargs)


@register
class Test(Optimizer):
    """reference optimizer.py Test — used by unit tests."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        weight._data = (weight + grad * self.rescale_grad)._data
        state._data = weight._data


class Updater:
    """Applies an optimizer with per-index state (reference optimizer.py
    Updater; serialized to kvstore servers via get/set_states)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        """Apply one update; returns the number of jit dispatches issued.

        Also accepts the reference's aggregated form — lists of
        (indices, grads, weights) — which routes through
        Optimizer.update_multi for ONE fused dispatch per bucket."""
        if isinstance(index, (list, tuple)):
            for i, w in zip(index, weight):
                if i not in self.states:
                    self.states[i] = \
                        self.optimizer.create_state_multi_precision(i, w)
            return self.optimizer.update_multi(
                list(index), list(weight), list(grad),
                [self.states[i] for i in index])
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])
        return 1

    def get_states(self, dump_optimizer=False):
        def conv(s):
            if isinstance(s, (list, tuple)):
                return tuple(conv(x) for x in s)
            return s.asnumpy() if isinstance(s, NDArray) else s

        payload = {k: conv(v) for k, v in self.states.items()}
        return pickle.dumps((payload, self.optimizer) if dump_optimizer else payload)

    def set_states(self, states):
        data = pickle.loads(states)
        if isinstance(data, tuple):
            data, self.optimizer = data

        def unconv(s):
            if isinstance(s, (list, tuple)):
                return tuple(unconv(x) for x in s)
            return nd.array(s) if isinstance(s, _np.ndarray) else s

        self.states = {k: unconv(v) for k, v in data.items()}


def get_updater(optimizer):
    return Updater(optimizer)
