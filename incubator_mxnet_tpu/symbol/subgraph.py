"""Generic subgraph partitioning: selector-driven graph rewrites.

Reference: src/operator/subgraph/subgraph_property.h:206 — a
SubgraphSelector state machine chooses connected node sets, and a
SubgraphProperty turns each set into one replacement node; backends
(MKLDNN fusion, TensorRT) plug in as properties. Round-3's verdict
flagged that this repo's purpose-built rewrites (AMP hook, quantize
pass, BN folding) each re-invent graph traversal; this module is the
one selector+replace framework future passes share.

TPU-native twist: a fused subgraph becomes ONE registered operator whose
fn evaluates the sub-symbol — so under jit the composite traces as a
unit (XLA still fuses across it; the value is structural: a pass can
quantize/replace/annotate the composite as a single node, and eager
executor dispatch pays one cached-jit call instead of N).

Usage:
    class ConvReluSelector(SubgraphSelector): ...
    class ConvReluProperty(SubgraphProperty):
        def create_subgraph_selector(self): return ConvReluSelector()
    register_subgraph_property("CONV_RELU", ConvReluProperty)
    sym2 = partition_graph(sym, "CONV_RELU")
"""
from __future__ import annotations

from ..base import MXNetError, Registry

__all__ = ["SubgraphSelector", "SubgraphProperty", "partition_graph",
           "register_subgraph_property", "SUBGRAPH_PROPERTIES",
           "ConvActProperty", "ElemwiseChainProperty"]

SUBGRAPH_PROPERTIES = Registry("subgraph_property")
_UID = 0


def register_subgraph_property(name, prop_cls):
    """Reference MXNET_REGISTER_SUBGRAPH_PROPERTY."""
    SUBGRAPH_PROPERTIES.register(prop_cls, name=name)
    return prop_cls


class SubgraphSelector:
    """Per-seed state machine (reference SubgraphSelector). `select`
    picks seed nodes; `select_input`/`select_output` decide whether to
    grow the current subgraph across an edge. Default: nothing."""

    def select(self, node):
        return False

    def select_input(self, node, input_node):
        return False

    def select_output(self, node, output_node):
        return False


class SubgraphProperty:
    """Reference SubgraphProperty: owns the selector and the replacement
    construction. Subclasses usually only override
    `create_subgraph_selector`; the default `create_subgraph_node` wraps
    the sub-symbol as one composite operator."""

    op_prefix = "_sg"

    def create_subgraph_selector(self):
        return SubgraphSelector()

    def create_subgraph_node(self, subgraph_sym, input_names, idx):
        """Returns (OpDef, attrs) for the replacement node. The default
        registers a fresh composite op evaluating `subgraph_sym`.
        The composite is train_aware so fused Dropout/activation modes
        follow the executor's is_train flag; note that BatchNorm
        batch-stat aux updates do NOT propagate out of a composite —
        partition_graph therefore refuses to fuse running-stat ops
        unless the property sets allow_train_stats."""
        from ..ops.registry import register

        n_out = len(subgraph_sym._outputs)
        runs = {m: subgraph_sym._build_eval(training=m)
                for m in (False, True)}

        def composite(*arrays, training=False, __sg_runs=runs,
                      __names=tuple(input_names), __n_out=n_out):
            outs, _ = __sg_runs[bool(training)](dict(zip(__names, arrays)))
            return tuple(outs) if __n_out > 1 else outs[0]

        # module-global counter: per-call indices would collide across
        # partition_graph invocations and silently overwrite the OPS +
        # INFER_PARAM_SHAPES entries of earlier partitions
        global _UID
        _UID += 1
        name = f"{self.op_prefix}_subgraph_{_UID}"
        opdef = register(name=name, train_aware=True)(composite)

        # parameter-shape inference must see THROUGH the composite: defer
        # to the sub-symbol's own inference (which applies the per-op
        # rules of the fused members, e.g. Convolution's weight shape)
        from .symbol import INFER_PARAM_SHAPES

        def _infer(attrs, in_shapes, _sub=subgraph_sym):
            try:
                shapes, _ = _sub._run_inference(dict(in_shapes), {}, True)
            except MXNetError:
                return {}
            if not shapes:
                return {}
            return {k: v for k, v in shapes.items()
                    if v is not None and k not in in_shapes
                    and not k.startswith("__out__")}

        INFER_PARAM_SHAPES[name] = _infer
        return opdef, {}


def _external_inputs(group):
    """External input entries (node, oi) feeding the group, deduped in
    stable order. (Group OUTPUT entries are computed by partition_graph
    itself — they need the consumer map.)"""
    member = {id(n) for n in group}
    ext_in, seen_in = [], set()
    for n in group:
        for e in n.inputs:
            if id(e[0]) not in member and (id(e[0]), e[1]) not in seen_in:
                seen_in.add((id(e[0]), e[1]))
                ext_in.append(e)
    return ext_in


def partition_graph(sym, prop, excluded_names=()):
    """Grow maximal selector-accepted connected subgraphs and replace
    each with its property's subgraph node (reference
    build_subgraph.cc BuildSubgraph). Convexity is enforced by
    restricting growth to edges that cannot create an external path
    back into the group (checked post-hoc, offenders dropped)."""
    from . import Symbol
    from .symbol import _Node, _topo

    if isinstance(prop, str):
        prop = SUBGRAPH_PROPERTIES.get(prop)()
    excluded = set(excluded_names)

    order = _topo(sym._outputs)
    consumers = {}
    for n in order:
        for (i, oi) in n.inputs:
            consumers.setdefault(id(i), []).append(n)
    for n, _ in sym._outputs:
        consumers.setdefault(id(n), []).append(None)   # exported

    from .symbol import AUX_INPUTS
    allow_stats = getattr(prop, "allow_train_stats", False)

    def fusable(n):
        if n.op is None or n.name in excluded:
            return False
        # running-stat ops (BatchNorm family) update aux state through
        # the executor; a composite would silently drop those updates
        return allow_stats or n.op.name not in AUX_INPUTS

    grouped = set()
    groups = []
    for seed in order:
        if not fusable(seed) or id(seed) in grouped:
            continue
        sel = prop.create_subgraph_selector()
        if not sel.select(seed):
            continue
        group = [seed]
        member = {id(seed)}
        frontier = [seed]
        while frontier:
            cur = frontier.pop()
            for (inp, _oi) in cur.inputs:
                if (inp.op is not None and fusable(inp)
                        and id(inp) not in member
                        and id(inp) not in grouped
                        and sel.select_input(cur, inp)):
                    member.add(id(inp))
                    group.append(inp)
                    frontier.append(inp)
            for out in consumers.get(id(cur), []):
                if (out is not None and fusable(out)
                        and id(out) not in member
                        and id(out) not in grouped
                        and sel.select_output(cur, out)):
                    member.add(id(out))
                    group.append(out)
                    frontier.append(out)
        # convexity repair: an external node both fed by and feeding the
        # group would be forced to run 'inside' the fused node's
        # schedule. Drop members downstream of any such node.
        group = _make_convex(group, order)
        if len(group) >= getattr(prop, "min_subgraph_size", 2):
            groups.append(group)
            grouped.update(id(n) for n in group)

    if not groups:
        return sym

    # build replacements
    mapping = {}   # (id(node), oi) -> (new_node, new_oi)
    for gi, group in enumerate(groups):
        member = {id(n) for n in group}
        ext_in = _external_inputs(group)
        # outputs: member entries consumed by non-members or exported
        out_entries, seen = [], set()
        for n in order:
            if id(n) in member:
                ext_consumer = any(
                    c is None or id(c) not in member
                    for c in consumers.get(id(n), []))
                if not ext_consumer:
                    continue
                # which output indices are used externally
                used = set()
                for c in consumers.get(id(n), []):
                    if c is None:
                        used.update(i for m, i in sym._outputs if m is n)
                    elif id(c) not in member:
                        used.update(oi for m, oi in c.inputs if m is n)
                for oi in sorted(used):
                    if (id(n), oi) not in seen:
                        seen.add((id(n), oi))
                        out_entries.append((n, oi))

        # sub-symbol: clone members, external entries -> fresh vars
        input_names = [f"__sg_in{i}" for i in range(len(ext_in))]
        ext_map = {(id(e[0]), e[1]): _Node(None, nm, {}, [])
                   for e, nm in zip(ext_in, input_names)}
        clones = {}

        def clone(node):
            if id(node) in clones:
                return clones[id(node)]
            ins = []
            for e in node.inputs:
                k = (id(e[0]), e[1])
                if k in ext_map:
                    ins.append((ext_map[k], 0))
                elif id(e[0]) in member:
                    ins.append((clone(e[0]), e[1]))
                else:
                    # an external entry not in ext_map can't happen:
                    # _external_inputs enumerated them all
                    raise MXNetError("subgraph clone missed an input")
            nn = _Node(node.op, node.name, node.attrs, ins,
                       extra=node.extra, arg_names=node.arg_names)
            clones[id(node)] = nn
            return nn

        sub_sym = Symbol([(clone(n), oi) for n, oi in out_entries])
        opdef, attrs = prop.create_subgraph_node(sub_sym, input_names, gi)
        comp = _Node(opdef, f"{prop.op_prefix}_subgraph{gi}", attrs,
                     list(ext_in), arg_names=list(input_names))
        for new_oi, (n, oi) in enumerate(out_entries):
            mapping[(id(n), oi)] = (comp, new_oi)

    # rebuild main graph
    rebuilt = {}

    def rebuild(node):
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        if node.op is None:
            rebuilt[id(node)] = node
            return node
        ins = []
        for e in node.inputs:
            k = (id(e[0]), e[1])
            if k in mapping:
                comp, noi = mapping[k]
                ins.append((rebuild(comp), noi))
            else:
                ins.append((rebuild(e[0]), e[1]))
        nn = _Node(node.op, node.name, node.attrs, ins,
                   extra=node.extra, arg_names=node.arg_names)
        rebuilt[id(node)] = nn
        return nn

    def rebuild_comp(comp):
        """Composite nodes' own external inputs may reference other
        mapped entries (chained groups)."""
        if id(comp) in rebuilt:
            return rebuilt[id(comp)]
        ins = []
        for e in comp.inputs:
            k = (id(e[0]), e[1])
            if k in mapping and mapping[k][0] is not comp:
                c2, noi = mapping[k]
                ins.append((rebuild_comp(c2), noi))
            else:
                ins.append((rebuild(e[0]), e[1]))
        comp.inputs[:] = ins
        rebuilt[id(comp)] = comp
        return comp

    new_outputs = []
    for n, i in sym._outputs:
        k = (id(n), i)
        if k in mapping:
            comp, noi = mapping[k]
            new_outputs.append((rebuild_comp(comp), noi))
        else:
            new_outputs.append((rebuild(n), i))
    return Symbol(new_outputs)


def _make_convex(group, order):
    """Drop members that would close an external cycle: for each
    non-member X with a member ancestor AND a member descendant, remove
    the members topologically at/after X."""
    pos = {id(n): i for i, n in enumerate(order)}
    cons = {}
    for n in order:
        for i, _ in n.inputs:
            cons.setdefault(id(i), []).append(n)
    changed = True
    while changed:
        changed = False
        member_now = {id(n) for n in group}
        fed_by_group = set()     # nodes with a member ancestor
        for n in order:
            if any(id(i) in member_now or id(i) in fed_by_group
                   for i, _ in n.inputs):
                fed_by_group.add(id(n))
        feeds_group = set()      # nodes with a member descendant
        for n in reversed(order):
            if any(id(c) in member_now or id(c) in feeds_group
                   for c in cons.get(id(n), [])):
                feeds_group.add(id(n))
        bad = [n for n in order
               if id(n) not in member_now
               and id(n) in fed_by_group and id(n) in feeds_group]
        if bad:
            cut = min(pos[id(b)] for b in bad)
            keep = [n for n in group if pos[id(n)] < cut]
            if len(keep) != len(group):
                group = keep
                changed = True
    return group


# ---------------------------------------------------------------------------
# stock properties (reference: subgraph/mkldnn/mkldnn_conv_property.h is the
# model for ConvAct; default_subgraph_property.cc for the generic grouping)
# ---------------------------------------------------------------------------

class _ConvActSelector(SubgraphSelector):
    """Convolution followed by a relu Activation, grown output-wards."""

    def __init__(self):
        self._state = None

    def select(self, node):
        if node.op is not None and node.op.name == "Convolution":
            self._state = "conv"
            return True
        return False

    def select_output(self, node, output_node):
        if (self._state == "conv" and output_node.op is not None
                and ((output_node.op.name == "Activation"
                      and output_node.attrs.get("act_type") == "relu")
                     or output_node.op.name == "relu")):
            self._state = "done"
            return True
        return False


class ConvActProperty(SubgraphProperty):
    op_prefix = "_sg_conv_act"

    def create_subgraph_selector(self):
        return _ConvActSelector()


_ELEMWISE = {"relu", "sigmoid", "tanh", "exp", "log", "negative", "abs",
             "square", "sqrt", "Activation", "broadcast_add",
             "broadcast_mul", "elemwise_add", "elemwise_mul"}


class _ElemwiseChainSelector(SubgraphSelector):
    def _ok(self, node):
        return node.op is not None and node.op.name in _ELEMWISE

    def select(self, node):
        return self._ok(node)

    def select_input(self, node, input_node):
        return self._ok(input_node)

    def select_output(self, node, output_node):
        return self._ok(output_node)


class ElemwiseChainProperty(SubgraphProperty):
    """Groups connected elementwise regions into one composite op —
    the structural analog of the reference's default property which
    groups whole o p islands."""
    op_prefix = "_sg_elemwise"

    def create_subgraph_selector(self):
        return _ElemwiseChainSelector()


register_subgraph_property("CONV_ACT", ConvActProperty)
register_subgraph_property("ELEMWISE_CHAIN", ElemwiseChainProperty)
