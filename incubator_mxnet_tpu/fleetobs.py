"""Fleet observability plane: cross-rank metrics aggregation, SLO
burn-rate alerting, and on-demand remote profiling.

PR 10 gave every *process* step-time attribution, compiler cost
accounting, and trace spans; this module pools them fleet-wide without
adding a single new connection. Each rank attaches a bounded metric
snapshot to the authenticated v2 kvstore heartbeat it already sends
(kvstore._hb_loop), the coordinator folds snapshots into a
FleetRegistry (kvstore_server heartbeat handler), and the registry
serves three operator surfaces:

  /metrics  fleet Prometheus text: per-rank families labeled rank="N"
            plus cross-rank aggregated phase histograms and quantiles
  /fleet    JSON: per-rank liveness, step rate, slow phase, MFU
  /alerts   JSON: the SLO engine's alert table

The SLO engine evaluates declarative specs (``p99(queue_wait) < 50ms``,
``mfu > 0.3``, ``straggler_lag < 1.5x``) with two burn-rate windows (one
evaluation interval and five); an alert fires only when BOTH windows
breach, so a single slow step cannot page anyone, and a sustained
breach fires within two evaluations. Transitions warn once per spec,
bump fault counters, and leave a flight-recorder breadcrumb.

Remote profiling closes the loop: ``fleet_profile_request`` queues a
control op that rides the next heartbeat *reply* to the target rank
(the coordinator never dials workers), the rank runs an attribution +
continuous-dump session for N steps, and ships the bounded trace back
over the MAC'd wire, where tools/trace_merge.py can merge it onto the
server clock.

Everything is gated behind MXNET_FLEET_OBS with the established
cached-bool pattern: off (the default), the heartbeat payload is
byte-identical to the non-fleet wire and no snapshot is ever built.
"""
from __future__ import annotations

import glob
import json
import logging
import os
import re
import shutil
import tempfile
import threading
from . import mxsan as _mxsan
import time
import weakref
from collections import deque

__all__ = ["enabled", "fleet_enable", "fleet_reset", "stats", "clear",
           "build_snapshot", "heartbeat_snapshot", "handle_command",
           "SLOSpec", "SLOEngine", "load_slo_specs", "FleetRegistry",
           "registries", "start_http", "stop_http", "rollout_alert"]

_log = logging.getLogger("incubator_mxnet_tpu.fleetobs")

# lock order (declared in tools/mxlint/lock_order.py): a FleetRegistry's
# self._lock may be held when the module _lock is taken (_bump from
# fold()); never the other way around
_lock = _mxsan.lock("fleetobs.py", "_lock")
_enabled = None

_counters = {
    "snapshots_built": 0,       # worker: snapshots attached to heartbeats
    "snapshots_skipped": 0,     # worker: beats skipped by the cadence knob
    "snapshots_folded": 0,      # coordinator: snapshots folded in
    "slo_evals": 0,             # coordinator: SLO engine evaluations
    "alerts_raised": 0,         # coordinator: ok -> firing transitions
    "alerts_resolved": 0,       # coordinator: firing -> ok transitions
    "profile_requests": 0,      # coordinator: control ops queued
    "profile_runs": 0,          # worker: remote profile sessions completed
    "profile_pushes": 0,        # coordinator: trace segments received
    "profile_fetches": 0,       # coordinator: stored traces handed out
    "profile_bytes": 0,         # coordinator: trace bytes received
    "rollout_alerts": 0,        # serving control plane: SLO-gated
    #                             rollout rollbacks and kindred events
}

# worker-side state: heartbeat cadence + one-profile-at-a-time latch
_beat_seq = 0
_profile_active = False


def enabled():
    """True when the fleet observability plane is on. The env var is
    read once and cached — the gate sits on the heartbeat hot path."""
    global _enabled
    if _enabled is None:
        from .util import getenv_bool
        _enabled = getenv_bool("MXNET_FLEET_OBS")
    return _enabled


def fleet_enable(on=True):
    """Force the plane on/off for this process (tests, operators);
    returns the previous effective state."""
    global _enabled
    prev = enabled()
    _enabled = bool(on)
    return prev


def fleet_reset():
    """Forget the cached MXNET_FLEET_OBS read and the worker-side beat
    cadence — the next enabled() consults the environment."""
    global _enabled, _beat_seq
    with _lock:
        _enabled = None
        _beat_seq = 0


def _bump(name, delta=1):
    with _lock:
        _counters[name] += delta


def stats():
    """Counter snapshot (dumps()/diagnose surface)."""
    with _lock:
        return dict(_counters)


def clear(stats=True):
    """dumps(reset=True) hook: restart the counter family."""
    if stats:
        with _lock:
            for k in _counters:
                _counters[k] = 0


# ---------------------------------------------------------------------------
# worker side: bounded heartbeat snapshots
# ---------------------------------------------------------------------------

SNAPSHOT_VERSION = 1
_MAX_PHASES = 16        # phase families shipped per snapshot
_MAX_COSTS = 8          # compiler cost records shipped per snapshot


def build_snapshot(step):
    """One bounded metric snapshot for a heartbeat: last-step phase
    vector, cumulative phase histograms (the registry diffs successive
    snapshots into deltas), MFU, exec-cache/tune counters, and the top
    compiler cost records. Every family is best-effort — a torn-down
    subsystem must never kill the heartbeat loop."""
    from . import profiler as _prof
    snap = {"v": SNAPSHOT_VERSION, "t": time.time(), "step": int(step)}
    try:
        phases = _prof.last_step_phases()
        if phases:
            top = sorted(phases.items(), key=lambda kv: -kv[1])
            snap["phases"] = {p: round(ms, 4) for p, ms in
                              top[:_MAX_PHASES]}
    except Exception:       # noqa: BLE001
        pass
    try:
        hist = _prof.phase_histograms()
        if hist:
            top = sorted(hist.items(), key=lambda kv: -kv[1]["sum_ms"])
            snap["hist"] = dict(top[:_MAX_PHASES])
    except Exception:       # noqa: BLE001
        pass
    try:
        mfu = _prof.mfu_stats()
        if mfu is not None:
            snap["mfu"] = mfu.get("mfu")
            snap["flops_per_step"] = mfu.get("flops_per_step")
    except Exception:       # noqa: BLE001
        pass
    try:
        counters = {}
        ec = _prof._exec_cache_stats()
        if ec:
            for k in ("hits", "misses", "disk_hits", "evictions"):
                counters[f"exec_cache_{k}"] = ec.get(k, 0)
        tn = _prof._tune_stats()
        if tn:
            for k in ("searches", "hits", "fallbacks"):
                counters[f"tune_{k}"] = tn.get(k, 0)
        ft = _prof._fault_stats()
        if ft:
            for k in ("heartbeats_sent", "faults_injected", "rejoins"):
                counters[f"fault_{k}"] = ft.get(k, 0)
        if counters:
            snap["counters"] = counters
    except Exception:       # noqa: BLE001
        pass
    try:
        costs = _prof.cost_stats()
        if costs:
            top = sorted(costs.items(),
                         key=lambda kv: -(kv[1].get("flops") or 0))
            snap["costs"] = {
                k: {"flops": v.get("flops"),
                    "bytes_accessed": v.get("bytes_accessed")}
                for k, v in top[:_MAX_COSTS]}
    except Exception:       # noqa: BLE001
        pass
    _bump("snapshots_built")
    return snap


def heartbeat_snapshot(step):
    """Cadence-gated build_snapshot for the heartbeat loop: returns the
    snapshot on every Nth beat (MXNET_FLEET_SNAPSHOT_INTERVAL), None on
    skipped beats. Callers must check enabled() first — this function
    assumes the plane is on."""
    global _beat_seq
    from .util import getenv_int
    every = max(1, getenv_int("MXNET_FLEET_SNAPSHOT_INTERVAL"))
    with _lock:
        seq = _beat_seq
        _beat_seq += 1
    if seq % every:
        _bump("snapshots_skipped")
        return None
    try:
        return build_snapshot(step)
    except Exception:       # noqa: BLE001 — never break the heartbeat
        _log.debug("fleet snapshot build failed", exc_info=True)
        return None


# ---------------------------------------------------------------------------
# SLO specs + burn-rate engine
# ---------------------------------------------------------------------------

_QUANTILE_RE = re.compile(
    r"^p(\d{1,2}(?:\.\d+)?)\s*\(\s*([\w.]+)\s*\)\s*"
    r"(<=|>=|<|>)\s*([\d.]+)\s*(ms|s|us)?$")
_LAG_RE = re.compile(r"^straggler_lag\s*(<=|>=|<|>)\s*([\d.]+)\s*x?$")
_GAUGE_RE = re.compile(r"^([\w.]+)\s*(<=|>=|<|>)\s*([\d.]+)$")

_UNIT_MS = {None: 1.0, "ms": 1.0, "s": 1e3, "us": 1e-3}


class SLOSpec:
    """One parsed SLO objective. `kind` is 'quantile' (phase-histogram
    percentile in ms), 'lag' (straggler step ratio), or 'gauge' (a
    scalar fleet metric like mfu). The spec states the GOOD condition;
    breach(value) is its negation."""

    __slots__ = ("raw", "kind", "metric", "q", "op", "threshold")

    def __init__(self, raw, kind, metric, q, op, threshold):
        self.raw = raw
        self.kind = kind
        self.metric = metric
        self.q = q
        self.op = op
        self.threshold = threshold

    @classmethod
    def parse(cls, text):
        text = text.strip()
        m = _QUANTILE_RE.match(text)
        if m:
            q, metric, op, val, unit = m.groups()
            # 'serve.queue_wait' names the same attribution phase the
            # batcher books as 'queue_wait'; accept both spellings
            metric = metric.rsplit(".", 1)[-1]
            return cls(text, "quantile", metric, float(q), op,
                       float(val) * _UNIT_MS[unit])
        m = _LAG_RE.match(text)
        if m:
            op, val = m.groups()
            return cls(text, "lag", "straggler_lag", None, op, float(val))
        m = _GAUGE_RE.match(text)
        if m:
            metric, op, val = m.groups()
            return cls(text, "gauge", metric.rsplit(".", 1)[-1], None,
                       op, float(val))
        raise ValueError(f"unparseable SLO spec {text!r}")

    def breach(self, value):
        good = {"<": value < self.threshold,
                "<=": value <= self.threshold,
                ">": value > self.threshold,
                ">=": value >= self.threshold}[self.op]
        return not good


DEFAULT_SLO_SPECS = ("straggler_lag < 1.5x",)


def load_slo_specs(path=None):
    """Parse the SLO spec file (MXNET_FLEET_SLO_PATH; one spec per
    line, '#' comments). Unreadable file or unparseable lines degrade
    to a warning + the built-in defaults — a bad spec file must not
    take down the coordinator."""
    from .util import getenv_str
    if path is None:
        path = getenv_str("MXNET_FLEET_SLO_PATH")
    if not path:
        return [SLOSpec.parse(s) for s in DEFAULT_SLO_SPECS]
    specs = []
    try:
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                try:
                    specs.append(SLOSpec.parse(line))
                except ValueError as e:
                    _log.warning("fleet SLO spec skipped: %s", e)
    except OSError as e:
        _log.warning("fleet SLO spec file %s unreadable (%s); using "
                     "defaults", path, e)
    return specs or [SLOSpec.parse(s) for s in DEFAULT_SLO_SPECS]


class SLOEngine:
    """Multi-window burn-rate evaluator over a set of SLOSpecs.

    Each evaluation appends one breach sample per spec (skipping specs
    whose metric has no data yet). An alert fires when the breach
    fraction is >= 0.5 in BOTH the short window (one evaluation
    interval) and the long window (five intervals), with at least two
    samples on the books — so a lone outlier evaluation never pages,
    and a sustained breach fires by the second evaluation. It resolves
    when both windows drop below the threshold again."""

    _BURN = 0.5
    _MIN_SAMPLES = 2

    def __init__(self, specs, interval_s=None):
        if interval_s is None:
            from .util import getenv_int
            interval_s = max(1, getenv_int("MXNET_FLEET_SLO_INTERVAL"))
        self.interval_s = float(interval_s)
        self.short_s = self.interval_s * 1.5   # tolerate eval jitter
        self.long_s = self.interval_s * 5
        self.specs = list(specs)
        self._samples = {s.raw: deque() for s in self.specs}
        self._state = {s.raw: {"state": "ok", "since": None, "value": None,
                               "burn_short": 0.0, "burn_long": 0.0}
                       for s in self.specs}
        self.breaches_total = 0

    def _burn(self, samples, window_s, now):
        hits = [b for t, b in samples if now - t <= window_s]
        if not hits:
            return 0.0
        return sum(hits) / len(hits)

    def evaluate(self, values, quantile_fn, now, wall=None):
        """One evaluation pass. `values` maps metric name -> scalar for
        gauge/lag specs; `quantile_fn(metric, q)` resolves quantile
        specs (ms) or returns None when the histogram is empty. Returns
        [(spec, "firing"|"resolved", value)] transitions."""
        if wall is None:
            wall = time.time()
        transitions = []
        for spec in self.specs:
            if spec.kind == "quantile":
                value = quantile_fn(spec.metric, spec.q)
            else:
                value = values.get(spec.metric)
            if value is None:
                continue
            breach = spec.breach(value)
            if breach:
                self.breaches_total += 1
            samples = self._samples[spec.raw]
            samples.append((now, breach))
            while samples and now - samples[0][0] > self.long_s:
                samples.popleft()
            st = self._state[spec.raw]
            st["value"] = value
            st["burn_short"] = self._burn(samples, self.short_s, now)
            st["burn_long"] = self._burn(samples, self.long_s, now)
            hot = (len(samples) >= self._MIN_SAMPLES
                   and st["burn_short"] >= self._BURN
                   and st["burn_long"] >= self._BURN)
            if hot and st["state"] == "ok":
                st["state"] = "firing"
                st["since"] = wall
                transitions.append((spec, "firing", value))
            elif not hot and st["state"] == "firing" \
                    and st["burn_short"] < self._BURN:
                st["state"] = "ok"
                st["since"] = wall
                transitions.append((spec, "resolved", value))
        return transitions

    def view(self):
        out = []
        for spec in self.specs:
            st = self._state[spec.raw]
            out.append({"spec": spec.raw, "kind": spec.kind,
                        "metric": spec.metric, "state": st["state"],
                        "since": st["since"], "value": st["value"],
                        "burn_short": round(st["burn_short"], 4),
                        "burn_long": round(st["burn_long"], 4)})
        return out

    def active(self):
        return [row for row in self.view() if row["state"] == "firing"]


# ---------------------------------------------------------------------------
# coordinator side: FleetRegistry
# ---------------------------------------------------------------------------

_registries = weakref.WeakSet()     # live registries (diagnose surface)


def registries():
    """Live FleetRegistry instances in this process (the coordinator
    has one per AsyncServer; workers none)."""
    return list(_registries)


class FleetRegistry:
    """Coordinator-side fold of per-rank heartbeat snapshots.

    Per (gen, rank) it keeps the latest snapshot-derived state (step,
    step rate, last-step phases, MFU, counters, cost records) plus the
    previous cumulative phase histogram so successive snapshots diff
    into fleet-wide bucket deltas — the cross-rank aggregate the
    quantile families and quantile SLO specs read. It also owns the
    control-op queue and the stored remote-profile traces."""

    LIVE_WINDOW_S = 30.0    # a rank silent this long is down in /fleet

    def __init__(self, specs=None, interval_s=None):
        self._lock = _mxsan.lock("fleetobs.py", "self._lock")
        self._ranks = {}        # (gen, rank) -> state dict
        self._fleet_hist = {}   # phase -> [count, sum_ms, buckets]
        self._pending = {}      # (gen, rank) -> control dict
        self._profiles = {}     # (gen, rank) -> stored trace record
        self._last_fetch = None
        self._req_seq = 0
        if specs is None:
            specs = load_slo_specs()
        self.engine = SLOEngine(specs, interval_s=interval_s)
        self._last_eval = None
        _registries.add(self)

    # -- folding --------------------------------------------------------

    def _diff_hist_locked(self, st, hist):
        """Fold the cumulative per-rank histograms into the fleet-wide
        delta aggregate. A count regression means the rank reset its
        attribution registry — restart the diff base from zero."""
        prev = st["hist_prev"]
        for phase, rec in hist.items():
            if not isinstance(rec, dict):
                continue
            buckets = rec.get("buckets")
            if not isinstance(buckets, list):
                continue
            count = rec.get("count", 0)
            sum_ms = rec.get("sum_ms", 0.0)
            p = prev.get(phase)
            if p is None or count < p["count"] \
                    or len(buckets) != len(p["buckets"]):
                p = {"count": 0, "sum_ms": 0.0,
                     "buckets": [0] * len(buckets)}
            agg = self._fleet_hist.get(phase)
            if agg is None or len(agg[2]) != len(buckets):
                agg = self._fleet_hist[phase] = [0, 0.0,
                                                 [0] * len(buckets)]
            agg[0] += max(0, count - p["count"])
            agg[1] += max(0.0, sum_ms - p["sum_ms"])
            for i, b in enumerate(buckets):
                agg[2][i] += max(0, b - p["buckets"][i])
            prev[phase] = {"count": count, "sum_ms": sum_ms,
                           "buckets": list(buckets)}

    def fold(self, gen, rank, step, snap, now=None):
        """Fold one heartbeat snapshot; returns a pending control dict
        for this rank (popped — control ops are one-shot) or None.
        Runs the SLO engine when an evaluation interval elapsed."""
        if not isinstance(snap, dict) or snap.get("v") != SNAPSHOT_VERSION:
            return None
        if now is None:
            now = time.monotonic()
        key = (int(gen), int(rank))
        step = int(step)
        transitions = []
        with self._lock:
            st = self._ranks.get(key)
            if st is None:
                st = self._ranks[key] = {
                    "step": 0, "step_rate": 0.0, "phases": {},
                    "mfu": None, "counters": {}, "costs": {},
                    "hist_prev": {}, "seen_mono": now,
                    "seen_wall": snap.get("t"), "snapshots": 0,
                }
            prev_step, prev_seen = st["step"], st["seen_mono"]
            if step > prev_step and now > prev_seen:
                st["step_rate"] = (step - prev_step) / (now - prev_seen)
            st["step"] = step
            st["seen_mono"] = now
            st["seen_wall"] = snap.get("t")
            st["snapshots"] += 1
            if isinstance(snap.get("phases"), dict):
                st["phases"] = snap["phases"]
            if "mfu" in snap:
                st["mfu"] = snap["mfu"]
            if isinstance(snap.get("counters"), dict):
                st["counters"] = snap["counters"]
            if isinstance(snap.get("costs"), dict):
                st["costs"] = snap["costs"]
            if isinstance(snap.get("hist"), dict):
                self._diff_hist_locked(st, snap["hist"])
            cmd = self._pending.pop(key, None)
            if self._last_eval is None \
                    or now - self._last_eval >= self.engine.interval_s:
                self._last_eval = now
                transitions = self.engine.evaluate(
                    self._metric_values_locked(now),
                    self._quantile_locked, now)
                _counters_bump = True
            else:
                _counters_bump = False
        _bump("snapshots_folded")
        if _counters_bump:
            _bump("slo_evals")
        for spec, what, value in transitions:
            self._alert_transition(spec, what, value)
        return cmd

    def _metric_values_locked(self, now):
        live = [st for st in self._ranks.values()
                if now - st["seen_mono"] <= self.LIVE_WINDOW_S]
        values = {}
        steps = [st["step"] for st in live]
        # the lag ratio needs two live ranks and a little warmup, or
        # startup skew (rank 0 registering first) reads as a straggler
        if len(steps) >= 2 and max(steps) >= 5:
            values["straggler_lag"] = max(steps) / max(min(steps), 1)
        mfus = [st["mfu"] for st in live
                if isinstance(st["mfu"], (int, float))]
        if mfus:
            values["mfu"] = sum(mfus) / len(mfus)
        return values

    def _quantile_locked(self, metric, q):
        """Percentile (ms) of the fleet-wide delta histogram for one
        phase, interpolated inside the winning log bucket (same trade
        as serve.LatencyHistogram.percentile). None when empty."""
        from . import profiler as _prof
        agg = self._fleet_hist.get(metric)
        if agg is None or agg[0] == 0:
            return None
        bounds = _prof.phase_bounds()
        total, _, buckets = agg
        rank = q / 100.0 * total
        seen = 0
        for i, c in enumerate(buckets):
            if seen + c >= rank and c > 0:
                lo = 0.0 if i == 0 else bounds[i - 1]
                hi = bounds[min(i, len(bounds) - 1)]
                return lo + (hi - lo) * min(1.0, (rank - seen) / c)
            seen += c
        return bounds[-1]

    def _alert_transition(self, spec, what, value):
        if what == "firing":
            _bump("alerts_raised")
            _log.warning("fleet SLO alert FIRING: %s (value %.4g)",
                         spec.raw, value)
            try:
                from . import fault as _fault
                _fault._bump("slo_alerts")
                _fault.flight_record("slo_alert", spec=spec.raw,
                                     value=value)
            except Exception:       # noqa: BLE001
                pass
        else:
            _bump("alerts_resolved")
            _log.warning("fleet SLO alert resolved: %s (value %.4g)",
                         spec.raw, value)
            try:
                from . import fault as _fault
                _fault.flight_record("slo_alert_resolved", spec=spec.raw,
                                     value=value)
            except Exception:       # noqa: BLE001
                pass

    # -- remote profiling -----------------------------------------------

    def request_profile(self, gen, rank, steps):
        """Queue a one-shot remote-profile control op for (gen, rank);
        it rides the rank's next heartbeat reply. Returns the request
        id the shipped trace will carry."""
        from .util import getenv_int
        steps = max(1, min(int(steps),
                           getenv_int("MXNET_FLEET_PROFILE_MAX_STEPS")))
        with self._lock:
            self._req_seq += 1
            rid = self._req_seq
            self._pending[(int(gen), int(rank))] = {
                "op": "profile", "id": rid, "steps": steps}
        _bump("profile_requests")
        return rid

    def store_profile(self, gen, rank, request_id, payload):
        """Accept one shipped trace (a chrome-trace JSON string).
        Oversized pushes are refused outright — the worker-side cap
        should have trimmed them, so size here means a bug or abuse."""
        from .util import getenv_int
        if not isinstance(payload, str):
            raise ValueError("profile payload must be a JSON string")
        cap = getenv_int("MXNET_FLEET_PROFILE_MAX_BYTES")
        nbytes = len(payload.encode("utf-8", "replace"))
        if nbytes > cap:
            raise ValueError(
                f"profile payload {nbytes} bytes exceeds "
                f"MXNET_FLEET_PROFILE_MAX_BYTES={cap}")
        with self._lock:
            self._profiles[(int(gen), int(rank))] = {
                "request_id": int(request_id), "trace": payload,
                "bytes": nbytes, "received_at": time.time()}
        _bump("profile_pushes")
        _bump("profile_bytes", nbytes)

    def fetch_profile(self, gen, rank):
        """Stored trace record for (gen, rank) or None; remembers the
        fetch for the diagnose surface."""
        with self._lock:
            rec = self._profiles.get((int(gen), int(rank)))
            if rec is not None:
                self._last_fetch = {"gen": int(gen), "rank": int(rank),
                                    "request_id": rec["request_id"],
                                    "at": time.time()}
                rec = dict(rec)
        if rec is not None:
            _bump("profile_fetches")
        return rec

    # -- operator views --------------------------------------------------

    def occupancy(self):
        """Small registry introspection dict (diagnose surface)."""
        with self._lock:
            return {"ranks": len(self._ranks),
                    "phases": len(self._fleet_hist),
                    "pending_commands": len(self._pending),
                    "stored_profiles": len(self._profiles),
                    "alerts_active": len(self.engine.active()),
                    "last_fetch": dict(self._last_fetch)
                    if self._last_fetch else None}

    def fleet_view(self, now=None):
        """The /fleet JSON: per-rank liveness, step rate, slow phase,
        MFU, plus the active-alert count."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            ranks = {}
            for (gen, rank), st in sorted(self._ranks.items()):
                age = now - st["seen_mono"]
                phases = st["phases"]
                slow = max(phases, key=phases.get) if phases else None
                ranks[str(rank)] = {
                    "gen": gen, "step": st["step"],
                    "step_rate": round(st["step_rate"], 4),
                    "alive": age <= self.LIVE_WINDOW_S,
                    "age_s": round(age, 3),
                    "slow_phase": slow,
                    "phases_ms": phases,
                    "mfu": st["mfu"],
                    "snapshots": st["snapshots"],
                }
            return {"ranks": ranks,
                    "alerts_active": len(self.engine.active())}

    def alerts_view(self):
        """The /alerts JSON: every spec's state + burn rates."""
        with self._lock:
            return {"alerts": self.engine.view(),
                    "breaches_total": self.engine.breaches_total}

    def render_prometheus(self, now=None):
        """Fleet families for the coordinator /metrics scrape: per-rank
        gauges labeled rank="N" plus the cross-rank aggregated phase
        histogram (spec-conformant cumulative le buckets) and quantile
        gauges derived from it."""
        from . import profiler as _prof
        if now is None:
            now = time.monotonic()
        esc = _prof._prom_label
        lines = []

        def family(name, mtype, help_text):
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")

        with self._lock:
            ranks = {k: dict(st) for k, st in sorted(self._ranks.items())}
            hist = {p: (v[0], v[1], list(v[2]))
                    for p, v in self._fleet_hist.items()}
            alerts = self.engine.view()
            breaches = self.engine.breaches_total

        family("mxnet_fleet_ranks", "gauge",
               "ranks the fleet registry has folded snapshots from")
        lines.append(f"mxnet_fleet_ranks {len(ranks)}")
        if ranks:
            family("mxnet_fleet_rank_up", "gauge",
                   "1 while the rank's snapshots are fresh")
            for (gen, rank), st in ranks.items():
                up = 1 if now - st["seen_mono"] <= self.LIVE_WINDOW_S else 0
                lines.append(f'mxnet_fleet_rank_up{{rank="{rank}"}} {up}')
            family("mxnet_fleet_rank_step", "gauge",
                   "latest step the rank reported")
            for (gen, rank), st in ranks.items():
                lines.append(
                    f'mxnet_fleet_rank_step{{rank="{rank}"}} {st["step"]}')
            family("mxnet_fleet_rank_step_rate", "gauge",
                   "steps per second between the rank's last snapshots")
            for (gen, rank), st in ranks.items():
                lines.append(f'mxnet_fleet_rank_step_rate{{rank="{rank}"}} '
                             f'{st["step_rate"]:.6g}')
            mfus = [(rank, st["mfu"]) for (gen, rank), st in ranks.items()
                    if isinstance(st["mfu"], (int, float))]
            if mfus:
                family("mxnet_fleet_rank_mfu", "gauge",
                       "rank-reported model FLOP utilization")
                for rank, mfu in mfus:
                    lines.append(
                        f'mxnet_fleet_rank_mfu{{rank="{rank}"}} {mfu:.6g}')
            phase_rows = [(rank, p, ms) for (gen, rank), st in ranks.items()
                          for p, ms in sorted(st["phases"].items())]
            if phase_rows:
                family("mxnet_fleet_rank_phase_ms", "gauge",
                       "rank's last-step attributed time per phase")
                for rank, p, ms in phase_rows:
                    lines.append(
                        f'mxnet_fleet_rank_phase_ms{{rank="{rank}",'
                        f'phase="{esc(p)}"}} {ms:.6g}')
        if hist:
            bounds = _prof.phase_bounds()
            family("mxnet_fleet_phase_ms", "histogram",
                   "cross-rank aggregated per-phase step time in ms")
            for p in sorted(hist):
                cnt, total, buckets = hist[p]
                lbl = esc(p)
                cum = 0
                for i, b in enumerate(bounds):
                    cum += buckets[i] if i < len(buckets) else 0
                    lines.append(f'mxnet_fleet_phase_ms_bucket{{'
                                 f'phase="{lbl}",le="{b:.6g}"}} {cum}')
                cum = sum(buckets)
                lines.append(f'mxnet_fleet_phase_ms_bucket{{phase="{lbl}",'
                             f'le="+Inf"}} {cum}')
                lines.append(f'mxnet_fleet_phase_ms_sum{{phase="{lbl}"}} '
                             f'{total:.3f}')
                lines.append(f'mxnet_fleet_phase_ms_count{{phase="{lbl}"}} '
                             f'{cnt}')
            family("mxnet_fleet_phase_ms_quantile", "gauge",
                   "cross-rank phase-time quantiles from the aggregated "
                   "histogram")
            with self._lock:
                for p in sorted(hist):
                    for q in (50.0, 90.0, 99.0):
                        v = self._quantile_locked(p, q)
                        if v is None:
                            continue
                        lines.append(
                            f'mxnet_fleet_phase_ms_quantile{{'
                            f'phase="{esc(p)}",q="{q / 100:g}"}} {v:.6g}')
        family("mxnet_fleet_slo_breaches_total", "counter",
               "SLO evaluations that found a spec in breach")
        lines.append(f"mxnet_fleet_slo_breaches_total {breaches}")
        family("mxnet_fleet_alerts_active", "gauge",
               "SLO alerts currently firing")
        lines.append(f"mxnet_fleet_alerts_active "
                     f"{sum(1 for a in alerts if a['state'] == 'firing')}")
        if alerts:
            family("mxnet_fleet_alert_firing", "gauge",
                   "1 while the labeled SLO spec's alert is firing")
            for a in alerts:
                lines.append(
                    f'mxnet_fleet_alert_firing{{spec="{esc(a["spec"])}"}} '
                    f'{1 if a["state"] == "firing" else 0}')
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# worker side: remote-profile control ops
# ---------------------------------------------------------------------------

def handle_command(cmd, kv, addr):
    """Act on a control dict delivered in a heartbeat reply. Profile
    commands run in a daemon thread (the heartbeat loop must keep
    beating while the session records); anything malformed is dropped.
    Never raises — it runs inside the heartbeat loop."""
    global _profile_active
    try:
        if not isinstance(cmd, dict) or cmd.get("op") != "profile":
            return
        with _lock:
            if _profile_active:
                return      # one session at a time; the op is one-shot
            _profile_active = True
        threading.Thread(target=_run_remote_profile,
                         args=(dict(cmd), kv, addr),
                         name="mxtpu-fleet-profile", daemon=True).start()
    except Exception:       # noqa: BLE001
        _log.debug("fleet control op dropped", exc_info=True)


def _cap_trace_events(events, cap_bytes):
    """Drop the oldest non-metadata events until the serialized trace
    fits the byte cap (metadata events — clock anchors, the
    remote_profile stamp — are load-bearing for the merge and kept)."""
    while True:
        payload = json.dumps({"traceEvents": events,
                              "displayTimeUnit": "ms"})
        if len(payload.encode("utf-8", "replace")) <= cap_bytes:
            return payload
        body = [i for i, ev in enumerate(events) if ev.get("ph") != "M"]
        if not body:
            return payload      # nothing left to trim; let the server judge
        drop = body[:max(1, len(body) // 8)]
        keep = set(range(len(events))) - set(drop)
        events[:] = [ev for i, ev in enumerate(events) if i in keep]


def _run_remote_profile(cmd, kv, addr):
    global _profile_active
    from . import profiler as _prof
    from .util import getenv_int
    tmpdir = None
    try:
        if _prof.is_running():
            _log.warning("remote profile request skipped: a local "
                         "profiling session is already running")
            return
        steps = max(1, min(int(cmd.get("steps", 1)),
                           getenv_int("MXNET_FLEET_PROFILE_MAX_STEPS")))
        max_s = max(1, getenv_int("MXNET_FLEET_PROFILE_MAX_SECONDS"))
        tmpdir = tempfile.mkdtemp(prefix="mxtpu-fleetprof-")
        base = os.path.join(tmpdir, "remote_profile.json")
        prev_attr = _prof.attribution_enable(True)
        _prof.set_config(filename=base, continuous_dump=True,
                         dump_period=0.25)
        _prof.start()
        start_step = kv._local_steps
        deadline = time.monotonic() + max_s
        while kv._local_steps - start_step < steps \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        _prof.stop()
        _prof.dump(finished=True)
        _prof.attribution_enable(prev_attr)
        events = []
        segments = sorted(glob.glob(
            os.path.join(tmpdir, "remote_profile*.json")))
        for path in segments:
            try:
                with open(path) as f:
                    events.extend(json.load(f).get("traceEvents", []))
            except Exception:       # noqa: BLE001 — torn segment
                pass
        events.append({"name": "remote_profile", "cat": "__metadata",
                       "ph": "M", "ts": 0, "pid": 0, "tid": 0,
                       "args": {"rank": int(kv.rank),
                                "request_id": int(cmd.get("id", 0)),
                                "steps": int(kv._local_steps - start_step),
                                "segments": len(segments)}})
        payload = _cap_trace_events(
            events, getenv_int("MXNET_FLEET_PROFILE_MAX_BYTES"))
        from .base import MXNetError
        from . import kvstore_server as _ksrv
        client = _ksrv.connect_async_server(addr)
        try:
            client.call("fleet_profile_push", kv._async_gen,
                        kv.rank, int(cmd.get("id", 0)), payload)
        except MXNetError as e:     # server refused (oversize, bad op)
            _log.warning("fleet profile push refused: %s", e)
        finally:
            client.close()
        _bump("profile_runs")
    except Exception:       # noqa: BLE001 — telemetry must not kill ranks
        _log.warning("remote profile session failed", exc_info=True)
    finally:
        with _lock:
            _profile_active = False
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def rollout_alert(name, **data):
    """Record a serving-rollout alert event (the control plane calls
    this on SLO-gated rollbacks): bumps the ``rollout_alerts`` counter
    and leaves a flight-recorder breadcrumb so a post-incident dump
    shows WHY traffic moved back."""
    _bump("rollout_alerts")
    from . import fault as _fault
    _fault.flight_record("rollout_alert", alert=name, **data)
    _log.warning("rollout alert %s: %s", name, data)


# ---------------------------------------------------------------------------
# coordinator HTTP surface (/metrics, /fleet, /alerts)
# ---------------------------------------------------------------------------

def start_http(registry, host="127.0.0.1", port=0, ready_fn=None):
    """Serve the registry over HTTP: /metrics (coordinator-local
    profiler families + the fleet families), /fleet, /alerts,
    /healthz (LIVENESS: 200 while the process answers at all) and
    /readyz (READINESS: gated on ``ready_fn`` when provided — e.g. a
    ModelServer's ``readiness`` composite of warm buckets + registered
    + not draining — else ready once the registry exists, which it does
    here). Returns the live HTTPServer; its bound address is
    server_address."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def _send(self, code, body, ctype):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            reg = self.server.fleet_registry
            try:
                if self.path == "/healthz":
                    self._send(200, "ok\n", "text/plain; charset=utf-8")
                elif self.path == "/readyz":
                    fn = self.server.ready_fn
                    ready, why = (True, []) if fn is None else fn()
                    self._send(200 if ready else 503,
                               json.dumps({"ready": bool(ready),
                                           "why": list(why)}),
                               "application/json")
                elif self.path == "/metrics":
                    from . import profiler as _prof
                    body = _prof.render_prometheus() \
                        + reg.render_prometheus()
                    self._send(200, body, "text/plain; version=0.0.4; "
                                          "charset=utf-8")
                elif self.path == "/fleet":
                    self._send(200, json.dumps(reg.fleet_view()),
                               "application/json")
                elif self.path == "/alerts":
                    self._send(200, json.dumps(reg.alerts_view()),
                               "application/json")
                else:
                    self._send(404, "not found\n",
                               "text/plain; charset=utf-8")
            except Exception as e:      # noqa: BLE001
                self._send(500, f"error: {e}\n",
                           "text/plain; charset=utf-8")

        def log_message(self, fmt, *args):
            _log.debug("fleet http: " + fmt, *args)

    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.daemon_threads = True
    srv.fleet_registry = registry
    srv.ready_fn = ready_fn
    threading.Thread(target=srv.serve_forever, name="mxtpu-fleet-http",
                     daemon=True).start()
    return srv


def stop_http(srv):
    if srv is None:
        return
    try:
        srv.shutdown()
        srv.server_close()
    except Exception:       # noqa: BLE001
        pass
