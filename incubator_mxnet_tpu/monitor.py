"""Monitor: per-op output statistics for debugging.

Reference: python/mxnet/monitor.py:146 — installs a stat callback on
executors (MXExecutorSetMonitorCallback), collects (batch, node, stat) rows
between tic()/toc(). Our Executor exposes the same set_monitor_callback hook.
"""
from __future__ import annotations

import logging
import re

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean() if hasattr(x, "abs") else x
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        exe.set_monitor_callback(self._stat_helper)
        self.exes.append(exe)

    def _stat_helper(self, name, arr):
        if not self.activated or not self.re_pattern.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                if self.re_pattern.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                v_list = [v_list]
            s = " ".join(str(float(v.asnumpy().reshape(-1)[0]))
                         if isinstance(v, NDArray) else str(v)
                         for v in v_list)
            res.append((n, k, s))
        self.queue = []
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
