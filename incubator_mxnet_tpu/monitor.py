"""Monitor: windowed per-op output statistics for debugging.

Capability parity with the reference monitor (python/mxnet/monitor.py —
executor stat callback + tic/toc windows around every `interval`-th
batch), designed around an explicit capture window: probes are reduced to
plain floats the moment they are captured (no deferred NDArray handling),
and arguments are swept once when the window closes. Executors attach via
the same `set_monitor_callback` hook.
"""
from __future__ import annotations

import logging
import re

__all__ = ["Monitor"]


def _default_stat(arr):
    """mean(|x|) — the reference's default summary statistic."""
    return arr.abs().mean() if hasattr(arr, "abs") else arr


def _to_text(value):
    """Render a captured statistic: NDArray-likes become their (scalar)
    value; lists render space-separated; everything else via str()."""
    items = value if isinstance(value, (list, tuple)) else [value]
    out = []
    for v in items:
        if hasattr(v, "asnumpy"):
            out.append(str(float(v.asnumpy().reshape(-1)[0])))
        else:
            out.append(str(v))
    return " ".join(out)


class Monitor:
    """Collect (batch, node_name, stat) rows for ops whose name matches
    `pattern`, on every `interval`-th batch between tic() and toc()."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = int(interval)
        self.stat_func = stat_func or _default_stat
        self.sort = sort
        self.re_pattern = re.compile(pattern)
        self._capturing = False
        self._batch = 0
        self._rows = []          # (batch, name, rendered stat)
        self._targets = []       # executors swept at window close
        # legacy attribute names some callers poke at
        self.activated = False
        self.step = 0
        self.exes = self._targets

    def install(self, exe):
        """Attach to an executor; its per-op outputs flow into the current
        window through the monitor callback."""
        exe.set_monitor_callback(self._capture)
        self._targets.append(exe)

    def _capture(self, name, arr):
        if self._capturing and self.re_pattern.match(name):
            self._rows.append((self._batch, name,
                               _to_text(self.stat_func(arr))))

    def tic(self):
        """Open a capture window if this batch index is due."""
        if self._batch % self.interval == 0:
            self._rows = []
            self._capturing = True
            self.activated = True
        self._batch += 1
        self.step = self._batch

    def toc(self):
        """Close the window: sweep matching executor arguments (weights),
        then return all rows as (batch, name, stat_string). Scalar stats
        are additionally published as `monitor:<name>` profiler counter
        series while the profiler is running, so activation/weight health
        lands in the same chrome trace / /metrics surface as everything
        else."""
        if not self._capturing:
            return []
        self._capturing = False
        self.activated = False
        for exe in self._targets:
            for name, arr in exe.arg_dict.items():
                if self.re_pattern.match(name):
                    self._rows.append((self._batch, name,
                                       _to_text(self.stat_func(arr))))
        rows, self._rows = self._rows, []
        if self.sort:
            rows.sort(key=lambda r: r[1])
        self._publish(rows)
        return rows

    @staticmethod
    def _publish(rows):
        from . import profiler
        if not profiler.is_running():
            return
        for _batch, name, stat in rows:
            head = stat.split(None, 1)[0] if stat else ""
            try:
                value = float(head)
            except ValueError:
                continue
            profiler._counter_sample(f"monitor:{name}", value)

    def toc_print(self):
        for batch, name, stat in self.toc():
            logging.info("Batch: %7d %30s %s", batch, name, stat)
