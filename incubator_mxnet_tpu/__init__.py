"""incubator_mxnet_tpu: a TPU-native deep-learning framework with the
capabilities of Apache MXNet (incubating).

Built from scratch on jax/XLA/Pallas/pjit (see SURVEY.md for the structural
analysis of the reference at /root/reference). The user surface mirrors MXNet
1.5 — `mx.nd`, `mx.autograd`, `mx.gluon`, `mx.sym`, `mx.mod`, KVStore — while
the runtime is idiomatic TPU: XLA owns scheduling/memory (no ThreadedEngine
port), `hybridize()` is jax.jit tracing, distributed training rides
jax.sharding Meshes and ICI collectives rather than NCCL/ps-lite.

Typical use:
    import incubator_mxnet_tpu as mx
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
"""
from __future__ import annotations

__version__ = "0.1.0"


def _configure_jax():
    # MXNet fp32 semantics: a float32 matmul/conv accumulates in float32.
    # JAX's default on TPU (and the virtual CPU backend) lowers fp32 dots to
    # bf16 passes; force full precision globally. Performance-critical paths
    # (bench, model zoo inference/training in bf16) pass bf16 inputs, which is
    # the idiomatic TPU way to use the MXU and is unaffected by this setting.
    # Opt-in fast fp32 (MXTPU_FP32_MATMUL=fast -> bf16_3x passes, =fastest
    # -> single bf16 pass): trades fp32 dot exactness for MXU throughput
    # while keeping every fp32 API surface — see docs/faq/float16.md and
    # runtime.set_fp32_matmul_mode().
    import os
    import jax
    # Honor JAX_PLATFORMS even when a site plugin (the axon TPU tunnel)
    # re-registered itself as the forced platform at interpreter startup:
    # without this, JAX_PLATFORMS=cpu processes still try to initialize
    # the tunnel backend and HANG when it is unreachable — observed as
    # example/test subprocess timeouts on a machine with a dead tunnel.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    from .runtime import set_fp32_matmul_mode
    from .util import getenv_str
    set_fp32_matmul_mode(getenv_str("MXTPU_FP32_MATMUL"))
    # Persistent XLA compilation cache: eager mode compiles one executable per
    # (op, shape) like the reference's cudnn autotune cache persists algo
    # choices (src/operator/nn/cudnn/cudnn_algoreg*) — ours persists whole
    # binaries across processes.
    cache_dir = os.path.expanduser(getenv_str("MXTPU_COMPILE_CACHE"))
    if cache_dir and cache_dir != "0":
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass


_configure_jax()

from . import base
from .base import MXNetError, MXTPUError
from . import context
from .context import Context, cpu, cpu_pinned, cpu_shared, current_context, gpu, tpu
from . import autograd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from .ndarray import random as _nd_random


class _RandomModule:
    """mx.random — seeds the global key chain (reference python/mxnet/random.py)."""
    seed = staticmethod(_nd_random.seed)
    uniform = staticmethod(_nd_random.uniform)
    normal = staticmethod(_nd_random.normal)
    randn = staticmethod(_nd_random.randn)
    randint = staticmethod(_nd_random.randint)
    shuffle = staticmethod(_nd_random.shuffle)
    multinomial = staticmethod(_nd_random.multinomial)


random = _RandomModule()


def __getattr__(name):
    # heavier subsystems load lazily to keep import light
    import importlib
    lazy = {
        "gluon": ".gluon",
        "optimizer": ".optimizer",
        "metric": ".metric",
        "initializer": ".initializer",
        "init": ".initializer",
        "lr_scheduler": ".lr_scheduler",
        "io": ".io",
        "image": ".image",
        "recordio": ".recordio",
        "kvstore": ".kvstore",
        "kv": ".kvstore",
        "symbol": ".symbol",
        "sym": ".symbol",
        "module": ".module",
        "mod": ".module",
        "model": ".model",
        "callback": ".callback",
        "monitor": ".monitor",
        "mon": ".monitor",
        "profiler": ".profiler",
        "compile_cache": ".compile_cache",
        "runtime": ".runtime",
        "parallel": ".parallel",
        "models": ".models",
        "serve": ".serve",
        "util": ".util",
        "utils": ".util",
        "test_utils": ".test_utils",
        "visualization": ".visualization",
        "viz": ".visualization",
        "contrib": ".contrib",
        "amp": ".contrib.amp",
        "engine": ".engine",
        "fault": ".fault",
        "executor": ".executor",
        "operator": ".operator",
        "np": ".numpy",
        "numpy": ".numpy",
        "npx": ".numpy_extension",
        "numpy_extension": ".numpy_extension",
        "torch": ".torch",
        "rtc": ".rtc",
    }
    if name in lazy:
        m = importlib.import_module(lazy[name], __name__)
        globals()[name] = m
        return m
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
