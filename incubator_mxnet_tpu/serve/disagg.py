"""Disaggregated prefill/decode serving: replica roles, chunked
prefill, and KV-page shipping.

Large serving fleets split the two phases of generation because they
want opposite hardware behavior: PREFILL is one big compute-bound
matmul burst per request, DECODE is thousands of tiny latency-bound
steps. Colocating them makes every long prompt a head-of-line stall
for every in-flight stream. This module supplies the pieces the
fleet-level split needs:

``PrefillPredictor``
    ONE chunked-prefill executable (compile-cache key
    ``serve:prefill_chunk[...]``): prompts are processed in fixed
    ``MXNET_DISAGG_PREFILL_CHUNK``-token chunks with traced
    start/length scalars, so any prompt length runs with zero
    retraces AND a decode-colocated scheduler can interleave a decode
    step between chunks — a long prompt never stalls a decode step.
    The chunk attends through the paged KV pool itself, which is what
    makes PREFIX RESUMPTION free: starting at ``covered`` tokens reads
    the cached prefix rows another stream already wrote.

``PrefillEngine``
    The prefill-role replica's engine: own page pool + PrefixCache
    (serve/prefix_cache.py), chunked prefill, and page EXPORT — the
    prompt's KV rows come back as host arrays, pages are released
    immediately (the cache keeps its holds), and the rows ship to the
    coordinator's page store over the MAC'd kvstore wire via the
    flat-packer (kvstore.ship_kv_pages). The decode replica fetches
    them by ship key and admits with ``DecodeScheduler.submit(...,
    kv_import=...)`` — no prefill recompute, TTFT already paid.

Roles (``prefill`` / ``decode`` / ``both``) are advertised through the
PR-12 ServeRegistry (serve_register wire v2) and consumed by the
role-aware Router policy.

Lock hierarchy (tools/mxlint/lock_order.py): engine ``self._lock``
outermost (serializes runs over the single pool), predictor
``self._compile_lock`` under it, the module counter ``_lock`` a leaf.
"""
from __future__ import annotations

import math
import threading

import numpy as _np

from ..base import MXNetError
from .. import util
from . import reqtrace as _rt
from .stats import ServingStats
from .. import mxsan as _mxsan

__all__ = ["PrefillPredictor", "PrefillEngine", "ship_key_for",
           "fetch_kv_import", "stats", "clear"]

_lock = _mxsan.lock("serve/disagg.py", "_lock")
_counters = {}


def _bump(name, n=1):
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def stats():
    """Module-level disagg counters (prefill runs, pages/bytes shipped,
    pages fetched) for diagnose.py and tests."""
    with _lock:
        return dict(_counters)


def clear():
    with _lock:
        _counters.clear()


def ship_key_for(model, request_id):
    """Page-store key for one prefill->decode handoff."""
    return f"kvship:{model}:{request_id}"


class PrefillPredictor:
    """The chunked-prefill executable over a DecodePredictor's geometry.

    Chunks write their KV rows into the sequence's pages (same padded
    out-of-bounds-drop scatter as decode), then attend over the WHOLE
    page-table window (max_pages_per_seq * page_size rows) with a causal
    mask on absolute positions — rows before ``start`` are read from
    the pages, so resuming after a cached prefix costs nothing extra.
    Exactly one executable regardless of prompt length or start offset:
    both are traced int32 scalars, only the chunk width is baked in.
    """

    def __init__(self, predictor, *, chunk=None):
        self.predictor = predictor
        self.chunk = int(chunk if chunk is not None
                         else util.getenv_int("MXNET_DISAGG_PREFILL_CHUNK"))
        if self.chunk < 1:
            raise MXNetError(f"prefill chunk {self.chunk}: need >= 1")
        self._compile_lock = _mxsan.lock(
            "serve/disagg.py", "self._compile_lock")
        self._fn = None
        self._warm = False

    def _key(self):
        p = self.predictor
        return (f"serve:prefill_chunk[c{self.chunk},"
                f"m{p.max_pages_per_seq},{p._geom_tag()}]")

    def _make_chunk(self):
        p = self.predictor
        h_, d_, ps, p_ = p.num_heads, p.head_dim, p.page_size, p.num_pages
        e_, c = p.embed, self.chunk
        lmax = p.max_pages_per_seq * ps
        scale = 1.0 / math.sqrt(d_)

        def call(params, tokens, start, n, k_pages, v_pages, ptrow):
            # tokens (1, C) int32 — prompt[start:start+C] zero-padded;
            # start, n () int32 TRACED (one executable for any offset
            # and prompt length); ptrow (max_pages_per_seq,) int32
            import jax
            import jax.numpy as jnp
            h = params["emb"][tokens[0]]                     # (C, E)
            q = (h @ params["wq"]).reshape(c, h_, d_)
            k = (h @ params["wk"]).reshape(c, h_, d_)
            v = (h @ params["wv"]).reshape(c, h_, d_)
            pos = start + jnp.arange(c, dtype=jnp.int32)     # absolute
            valid = pos < n
            flat = ptrow[pos // ps] * ps + pos % ps
            flat = jnp.where(valid, flat, p_ * ps)
            kp = k_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                k, mode="drop")
            vp = v_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                v, mode="drop")
            # attend over the whole page-table window: rows < start are
            # the cached/previous-chunk context read back from pages
            ctx = jnp.arange(lmax, dtype=jnp.int32)
            cflat = ptrow[ctx // ps] * ps + ctx % ps
            kc = kp[cflat]                                   # (Lmax, H, D)
            vc = vp[cflat]
            s = jnp.einsum("qhd,khd->hqk", q * scale, kc)
            mask = (ctx[None, :] <= pos[:, None]) & valid[:, None]
            s = jnp.where(mask[None], s, -1e30)
            pr = jax.nn.softmax(s, axis=-1)
            a = jnp.einsum("hqk,khd->qhd", pr, vc).reshape(c, e_)
            o = a @ params["wo"] + h
            logits = o @ params["w_out"]                     # (C, V)
            last = jnp.clip(n - 1 - start, 0, c - 1)
            nxt = jnp.argmax(logits[last], axis=-1).astype(jnp.int32)
            return (nxt, kp.reshape(p_, ps, h_, d_),
                    vp.reshape(p_, ps, h_, d_))

        return call

    def _exec_chunk(self):
        with self._compile_lock:
            if self._fn is None:
                from .. import compile_cache as _cc
                self._fn = _cc.cached_jit(self._key(), self._make_chunk())
        return self._fn

    def warmup(self):
        """AOT-compile the chunk executable; returns
        {"prefill_chunk": kind} with kind from compile_cache.warmup
        ("hit"/"disk"/"miss") — kept SEPARATE from
        DecodePredictor.warmup() so decode-only replicas never build
        it."""
        import jax
        import jax.numpy as jnp
        p = self.predictor
        i32 = jnp.int32
        kv = jax.ShapeDtypeStruct((p.num_pages, p.page_size, p.num_heads,
                                   p.head_dim), jnp.float32)
        kind = self._exec_chunk().warmup(
            p._param_vals,
            jax.ShapeDtypeStruct((1, self.chunk), i32),
            jax.ShapeDtypeStruct((), i32), jax.ShapeDtypeStruct((), i32),
            kv, kv, jax.ShapeDtypeStruct((p.max_pages_per_seq,), i32))
        self._warm = True
        return {"prefill_chunk": kind}

    @property
    def is_warm(self):
        return self._warm

    def prefill_chunk(self, prompt, start, k_pages, v_pages, ptrow):
        """Run ONE chunk at offset ``start``; returns (next-token pick,
        updated pools). The pick is only meaningful once the chunk
        covers position len(prompt)-1."""
        import jax.numpy as jnp
        n = len(prompt)
        toks = _np.zeros((1, self.chunk), _np.int32)
        seg = prompt[start:start + self.chunk]
        toks[0, :len(seg)] = seg
        fn = self._exec_chunk()
        with _rt.span("prefill_chunk", args={"start": int(start),
                                             "tokens": int(len(seg))}):
            nxt, kp, vp = fn(self.predictor._param_vals, jnp.asarray(toks),
                             jnp.asarray(start, jnp.int32),
                             jnp.asarray(n, jnp.int32), k_pages, v_pages,
                             jnp.asarray(ptrow, jnp.int32))
        self._warm = True
        _bump("chunks_total")
        return int(nxt), kp, vp

    def prefill(self, prompt, start, k_pages, v_pages, ptrow):
        """All chunks from ``start`` to the end of the prompt; returns
        (next token, updated pools)."""
        nxt = None
        for lo in range(start, len(prompt), self.chunk):
            nxt, k_pages, v_pages = self.prefill_chunk(
                prompt, lo, k_pages, v_pages, ptrow)
        return nxt, k_pages, v_pages


class PrefillEngine:
    """A prefill-role replica's engine: chunked prefill over its own
    page pool + prefix cache, exporting each prompt's KV rows for
    shipment to a decode replica.

    ``run`` returns a host-side export bundle and immediately releases
    the stream's page holds (the cache keeps its own), so the pool's
    steady-state occupancy is just the cached prefix set — a prefill
    replica's capacity is compute, not memory.
    """

    def __init__(self, predictor, *, chunk=None, stats=None,
                 prefix_cache=None, name="prefill"):
        from .decode import PageAllocator
        self.predictor = predictor
        self.chunker = PrefillPredictor(predictor, chunk=chunk)
        self.allocator = PageAllocator(predictor.num_pages)
        self.stats = stats if stats is not None else ServingStats(name)
        if prefix_cache is None:
            prefix_cache = util.getenv_bool("MXNET_PREFIX_CACHE")
        if prefix_cache is True:
            from .prefix_cache import PrefixCache
            prefix_cache = PrefixCache(self.allocator, predictor.page_size)
        self.prefix_cache = prefix_cache or None
        self._lock = _mxsan.lock("serve/disagg.py", "self._lock")
        self._k_pages = None
        self._v_pages = None
        self.stats.set_gauge("kv_pages_total", predictor.num_pages)

    def warmup(self):
        return self.chunker.warmup()

    @property
    def is_warm(self):
        return self.chunker.is_warm

    def run(self, prompt, max_new_tokens=None):
        """Prefill one prompt (prefix-cache assisted, chunked); returns
        the export bundle {"next_token", "n", "k_rows", "v_rows",
        "cached_tokens"} with (ceil(n/page_size), page_size, H, D)
        float32 host rows. Raises Overloaded when the pool cannot hold
        the prompt (retryable — the router picks another replica)."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("empty prompt")
        p = self.predictor
        n = len(prompt)
        m = math.ceil(n / p.page_size)
        if m > p.max_pages_per_seq:
            raise MXNetError(
                f"prompt needs {m} KV pages, per-sequence cap is "
                f"{p.max_pages_per_seq} (MXNET_KV_PAGES_PER_SEQ)")
        with self._lock:
            if self._k_pages is None:
                self._k_pages, self._v_pages = p.kv_pool()
            t0 = _now()
            pages, covered = self._claim_locked(prompt, m)
            nxt, self._k_pages, self._v_pages = self.chunker.prefill(
                prompt, covered, self._k_pages, self._v_pages,
                self._ptrow(pages))
            if self.prefix_cache is not None:
                self.prefix_cache.insert(prompt, pages, n)
            idx = _np.asarray(pages, _np.int64)
            k_rows = _np.asarray(self._k_pages[idx])
            v_rows = _np.asarray(self._v_pages[idx])
            self.allocator.free(pages)
            self.stats.prefill_time.observe(_now() - t0)
        self.stats.incr("requests_total")
        self.stats.incr("responses_ok")
        self.stats.set_gauge("kv_pages_free", self.allocator.free_count)
        self.stats.set_gauge("kv_pages_used", self.allocator.used_count)
        self.stats.set_gauge("kv_pages_shared", self.allocator.shared_count)
        _bump("prefill_requests")
        if covered:
            _bump("prefix_tokens_reused", covered)
        return {"next_token": int(nxt), "n": n, "k_rows": k_rows,
                "v_rows": v_rows, "cached_tokens": covered}

    def _ptrow(self, pages):
        row = _np.zeros(self.predictor.max_pages_per_seq, _np.int32)
        row[:len(pages)] = pages
        return row

    def _claim_locked(self, prompt, m):
        """Claim exactly ``m`` pages for the prompt: shared cached
        prefix + CoW fork of a partial tail + fresh pages for the rest
        (the same discipline as DecodeScheduler._claim_pages_locked)."""
        if self.prefix_cache is None:
            return self.allocator.alloc(m), 0
        pages, covered, partial = self.prefix_cache.lookup(prompt)
        try:
            if partial:
                fresh, copied = self.allocator.fork(pages[-1])
                if copied:
                    src = pages[-1]
                    self._k_pages = self._k_pages.at[fresh].set(
                        self._k_pages[src])
                    self._v_pages = self._v_pages.at[fresh].set(
                        self._v_pages[src])
                    self.prefix_cache.note_cow_fork()
                pages = pages[:-1] + [fresh]
            extra = m - len(pages)
            if extra > 0:
                pages = pages + self.allocator.alloc(extra)
        except Exception:
            if pages:
                self.allocator.free(pages)
            raise
        return pages, covered

    # -- shipping -------------------------------------------------------
    def ship(self, client, key, export):
        """Push one export bundle to the coordinator's page store over
        the MAC'd wire (kvstore.ship_kv_pages / flat-packer). Returns
        the server receipt."""
        from .. import kvstore as _kv
        with _rt.span("kv_ship", args={"pages": len(export["k_rows"])}):
            receipt = _kv.ship_kv_pages(
                client, key, export["k_rows"], export["v_rows"],
                meta={"n": export["n"], "next_token": export["next_token"],
                      "page_size": self.predictor.page_size})
        _bump("pages_shipped", len(export["k_rows"]))
        _bump("bytes_shipped", int(receipt.get("bytes", 0)))
        return receipt


def fetch_kv_import(client, key, delete=False):
    """Decode-replica side: fetch a shipped bundle and shape it as the
    ``kv_import`` dict DecodeScheduler.submit expects. Returns None on
    an unknown/expired key (caller falls back to local prefill)."""
    from .. import kvstore as _kv
    got = _kv.fetch_kv_pages(client, key, delete=delete)
    if got is None:
        _bump("fetch_misses")
        return None
    k_rows, v_rows, meta = got
    _bump("pages_fetched", len(k_rows))
    return {"k_rows": k_rows, "v_rows": v_rows, "n": int(meta["n"]),
            "next_token": int(meta["next_token"])}


def _now():
    import time
    return time.monotonic()
