"""Serving telemetry: latency histograms, queue/shed counters.

The reference exposes serving health only through the engine profiler;
production TPU serving needs request-level numbers (TensorFlow-Serving
style): p50/p95/p99 latency, queue depth, batch occupancy, shed counts.
Histograms are log-spaced fixed buckets so `observe` is O(1), lock-held
for a few adds, and percentiles are read without stopping the world.

Everything is published through `profiler.Counter`s (one sample per
batch dispatch, NOT per request, so the profiler's counter series stays
bounded under load) — `profiler.dumps()` then shows the serving table
next to the op stats, and `profiler.dump()` places the series on the
chrome trace timeline.
"""
from __future__ import annotations

import logging
import math
import threading
from .. import mxsan as _mxsan

__all__ = ["LatencyHistogram", "ServingStats", "reqtrace_exemplar_lines"]

_log = logging.getLogger("incubator_mxnet_tpu.serve")


def reqtrace_exemplar_lines(hist, labels, histogram):
    """``mxnet_reqtrace_slow_exemplar`` exposition for one histogram's
    slowest-K traced requests per bucket (serve/reqtrace.py supplies the
    trace ids). Empty — and absent from /metrics — until a traced sample
    was observed, so a gate-off scrape is unchanged."""
    ex = hist.exemplars()
    if not ex:
        return []
    lines = ["# HELP mxnet_reqtrace_slow_exemplar slowest traced "
             "requests per latency bucket (value in ms)",
             "# TYPE mxnet_reqtrace_slow_exemplar gauge"]
    for bound in sorted(ex):
        le = "+Inf" if bound == float("inf") else f"{bound * 1e3:.6g}"
        for secs, trace in ex[bound]:
            lines.append(f'mxnet_reqtrace_slow_exemplar{{{labels},'
                         f'histogram="{histogram}",le="{le}",'
                         f'trace="{trace}"}} {secs * 1e3:.6g}')
    return lines


class LatencyHistogram:
    """Fixed log-spaced latency buckets (10us .. ~105s, x1.5 steps).

    `percentile` linearly interpolates inside the winning bucket, which
    bounds the error to one bucket width (<= 50% relative) — the standard
    Prometheus-histogram trade for lock-free-ish hot paths.
    """

    _GROWTH = 1.5
    _FLOOR = 10e-6  # seconds
    _EXEMPLAR_K = 3  # slowest trace ids retained per bucket

    def __init__(self, nbuckets=40):
        self._bounds = [self._FLOOR * self._GROWTH ** i
                        for i in range(nbuckets)]
        self._counts = [0] * (nbuckets + 1)  # +1: overflow bucket
        self._lock = _mxsan.lock("serve/stats.py", "self._lock")
        self._exemplars = None  # bucket idx -> [(seconds, trace_id)] desc
        self.count = 0
        self.sum = 0.0

    def _index(self, seconds):
        if seconds <= self._FLOOR:
            return 0
        i = int(math.log(seconds / self._FLOOR) / math.log(self._GROWTH)) + 1
        return min(i, len(self._bounds))

    def observe(self, seconds, trace=None):
        """Record one sample. `trace` (a reqtrace trace id, only passed
        for head-sampled requests) retains the slowest-K exemplars per
        bucket so a fat histogram tail names the requests that built it;
        the default None keeps the traced-off hot path allocation-free."""
        seconds = max(0.0, float(seconds))
        with self._lock:
            idx = self._index(seconds)
            self._counts[idx] += 1
            self.count += 1
            self.sum += seconds
            if trace is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                slot = self._exemplars.setdefault(idx, [])
                slot.append((seconds, str(trace)))
                slot.sort(reverse=True)
                del slot[self._EXEMPLAR_K:]

    def exemplars(self):
        """{bucket upper bound (seconds) -> [(seconds, trace_id), ...]
        slowest-first}; empty dict until a traced sample was observed."""
        with self._lock:
            if not self._exemplars:
                return {}
            out = {}
            for idx, slot in self._exemplars.items():
                bound = (self._bounds[idx] if idx < len(self._bounds)
                         else float("inf"))
                out[bound] = list(slot)
            return out

    def percentile(self, q):
        """q in [0, 100] -> seconds (0.0 when empty)."""
        with self._lock:
            total = self.count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        seen = 0
        for i, c in enumerate(counts):
            if seen + c >= rank and c > 0:
                lo = 0.0 if i == 0 else self._bounds[i - 1]
                hi = self._bounds[min(i, len(self._bounds) - 1)]
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(1.0, frac)
            seen += c
        return self._bounds[-1]

    @property
    def mean(self):
        with self._lock:
            return self.sum / self.count if self.count else 0.0

    def snapshot_state(self):
        """Consistent raw view for exposition: {"bounds" (upper bounds,
        seconds), "counts" (per-bucket, +1 overflow), "count", "sum"}."""
        with self._lock:
            return {"bounds": list(self._bounds),
                    "counts": list(self._counts),
                    "count": self.count, "sum": self.sum}


class ServingStats:
    """Aggregated serving counters + histograms for one model endpoint.

    Counter semantics:
      requests_total     every submit() that entered the system
      responses_ok       completed with a result
      shed_queue_full    rejected at admission (bounded queue full)
      shed_deadline      expired before or during dispatch
      shed_draining      rejected while admission was paused (drain/swap)
      errors             predict raised
      batches_total      compiled-bucket dispatches
      padded_rows_total  bucket_size - real rows, summed over batches
      queue_depth        gauge, sampled at publish time
      batch_occupancy    real_rows / bucket_size of the last batch

    Decode-side counters (DecodeScheduler; zero on predict-only
    endpoints and omitted from /metrics until a stream is seen):
      decode_streams_total   streams admitted to the queue
      decode_tokens_total    tokens delivered (prefill + decode steps)
      decode_retired_total   streams retired (ok, error, or deadline)
      shed_projected         sheds by the projected-queue-wait bound
      decode_active          gauge, occupied decode slots
      kv_pages_live/total    gauge pair, KV page pool occupancy
      kv_pages_free/used/shared  allocator occupancy gauges (shared =
                             refcount >= 2: prefix-cache overlap)
      kv_pages_imported_total    pages admitted pre-filled (disagg ship)
      prefix_cache_hits/misses/prefix_tokens_saved  prefix-cache gauges
    plus four histograms: ttft (submit -> first token), token_latency
    (inter-token gap), prefill_time, decode_step_time.

    Speculative-decode counters (spec schedulers only; omitted from
    every surface until a spec step is seen):
      spec_steps_total           draft+verify iterations dispatched
      spec_tokens_proposed_total draft tokens offered to verify
      spec_tokens_accepted_total draft tokens the target agreed with
      spec_adaptive_k            gauge, mean per-stream draft depth
    plus three histograms: spec_accept_rate (per-stream per-step accept
    FRACTION, 0..1 — not a latency), spec_draft_time, spec_verify_time.
    """

    def __init__(self, name="serve"):
        self.name = name
        self._lock = _mxsan.lock("serve/stats.py", "self._lock")
        self.latency = LatencyHistogram()      # end-to-end (submit->result)
        self.queue_wait = LatencyHistogram()   # submit->dispatch
        self.forward_time = LatencyHistogram()  # batched predict call
        self.ttft = LatencyHistogram()          # submit->first token
        self.token_latency = LatencyHistogram()  # gap between tokens
        self.prefill_time = LatencyHistogram()   # prompt executable
        self.decode_step_time = LatencyHistogram()  # slot-batch step
        # spec decode: accept rate holds a FRACTION (0..1), reusing the
        # log-spaced histogram for O(1) observe + percentile reads
        self.spec_accept_rate = LatencyHistogram()
        self.spec_draft_time = LatencyHistogram()   # host-side propose
        self.spec_verify_time = LatencyHistogram()  # batched verify
        self.requests_total = 0
        self.responses_ok = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.shed_draining = 0
        self.shed_projected = 0
        self.errors = 0
        self.batches_total = 0
        self.padded_rows_total = 0
        self.queue_depth = 0
        self.batch_occupancy = 0.0
        self.decode_streams_total = 0
        self.decode_tokens_total = 0
        self.decode_retired_total = 0
        self.decode_active = 0
        self.kv_pages_live = 0
        self.kv_pages_total = 0
        self.kv_page_occupancy = 0.0
        self.kv_pages_free = 0
        self.kv_pages_used = 0
        self.kv_pages_shared = 0
        self.kv_pages_imported_total = 0
        self.prefix_cache_hits = 0
        self.prefix_cache_misses = 0
        self.prefix_tokens_saved = 0
        self.spec_steps_total = 0
        self.spec_tokens_proposed_total = 0
        self.spec_tokens_accepted_total = 0
        self.spec_adaptive_k = 0.0
        self._profiler_counters = {}
        # per-bucket latency split: how much of the end-to-end time each
        # compiled bucket spends WAITING vs ON DEVICE — a queue-bound
        # endpoint and a compute-bound one need opposite remedies
        self._bucket_hists = {}     # bucket -> (queue_wait LH, device LH)
        self._queue_warned = False

    # -- recording (called by batcher/server) ---------------------------
    def incr(self, field, n=1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def set_gauge(self, field, value):
        with self._lock:
            setattr(self, field, value)

    def observe_bucket(self, bucket, queue_waits, device_seconds):
        """Record one dispatch of `bucket`: each request's queue wait
        (seconds) and the single batched device/forward time."""
        bucket = int(bucket)
        with self._lock:
            pair = self._bucket_hists.get(bucket)
            if pair is None:
                pair = self._bucket_hists[bucket] = (LatencyHistogram(),
                                                     LatencyHistogram())
        qh, dh = pair
        for s in queue_waits:
            qh.observe(s)
        dh.observe(device_seconds)

    def bucket_snapshot(self):
        """{bucket: {queue_wait_p50_ms, queue_wait_p95_ms, device_p50_ms,
        device_p95_ms, dispatches}} for every bucket seen so far."""
        with self._lock:
            pairs = sorted(self._bucket_hists.items())
        return {b: {"queue_wait_p50_ms": round(qh.percentile(50) * 1e3, 4),
                    "queue_wait_p95_ms": round(qh.percentile(95) * 1e3, 4),
                    "device_p50_ms": round(dh.percentile(50) * 1e3, 4),
                    "device_p95_ms": round(dh.percentile(95) * 1e3, 4),
                    "dispatches": dh.count}
                for b, (qh, dh) in pairs}

    def _warn_if_queue_bound(self):
        """Warn ONCE when queue_wait p95 exceeds device p95: requests
        spend longer waiting for a bucket slot than being computed — the
        endpoint needs replicas / larger buckets, not a faster model."""
        if self._queue_warned or self.queue_wait.count < 20:
            return
        qp95 = self.queue_wait.percentile(95)
        dp95 = self.forward_time.percentile(95)
        if dp95 > 0.0 and qp95 > dp95:
            self._queue_warned = True
            _log.warning(
                "[%s] queue_wait p95 %.2f ms exceeds device p95 %.2f ms: "
                "the endpoint is queue-bound; add replicas, widen the "
                "bucket ladder, or raise max_latency_ms",
                self.name, qp95 * 1e3, dp95 * 1e3)

    # -- export ---------------------------------------------------------
    def snapshot(self):
        with self._lock:
            snap = {
                "requests_total": self.requests_total,
                "responses_ok": self.responses_ok,
                "shed_queue_full": self.shed_queue_full,
                "shed_deadline": self.shed_deadline,
                "shed_draining": self.shed_draining,
                "shed_projected": self.shed_projected,
                "shed_total": (self.shed_queue_full + self.shed_deadline
                               + self.shed_draining + self.shed_projected),
                "errors": self.errors,
                "batches_total": self.batches_total,
                "padded_rows_total": self.padded_rows_total,
                "queue_depth": self.queue_depth,
                "batch_occupancy": round(self.batch_occupancy, 4),
                "decode_streams_total": self.decode_streams_total,
                "decode_tokens_total": self.decode_tokens_total,
                "decode_retired_total": self.decode_retired_total,
                "decode_active": self.decode_active,
                "kv_pages_live": self.kv_pages_live,
                "kv_pages_total": self.kv_pages_total,
                "kv_page_occupancy": round(self.kv_page_occupancy, 4),
                "kv_pages_free": self.kv_pages_free,
                "kv_pages_used": self.kv_pages_used,
                "kv_pages_shared": self.kv_pages_shared,
                "kv_pages_imported_total": self.kv_pages_imported_total,
                "prefix_cache_hits": self.prefix_cache_hits,
                "prefix_cache_misses": self.prefix_cache_misses,
                "prefix_tokens_saved": self.prefix_tokens_saved,
                "spec_steps_total": self.spec_steps_total,
                "spec_tokens_proposed_total":
                    self.spec_tokens_proposed_total,
                "spec_tokens_accepted_total":
                    self.spec_tokens_accepted_total,
                "spec_adaptive_k": round(self.spec_adaptive_k, 4),
            }
        for prefix, h in (("latency", self.latency),
                          ("queue_wait", self.queue_wait),
                          ("forward", self.forward_time),
                          ("ttft", self.ttft),
                          ("token", self.token_latency),
                          ("prefill", self.prefill_time),
                          ("decode_step", self.decode_step_time),
                          ("spec_draft", self.spec_draft_time),
                          ("spec_verify", self.spec_verify_time)):
            snap[f"{prefix}_p50_ms"] = round(h.percentile(50) * 1e3, 4)
            snap[f"{prefix}_p95_ms"] = round(h.percentile(95) * 1e3, 4)
            snap[f"{prefix}_p99_ms"] = round(h.percentile(99) * 1e3, 4)
            snap[f"{prefix}_mean_ms"] = round(h.mean * 1e3, 4)
        # accept rate is a fraction, not a latency: export unscaled
        snap["spec_accept_rate_p50"] = \
            round(self.spec_accept_rate.percentile(50), 4)
        snap["spec_accept_rate_mean"] = \
            round(self.spec_accept_rate.mean, 4)
        for b, row in self.bucket_snapshot().items():
            for k, v in row.items():
                snap[f"bucket{b}_{k}"] = v
        return snap

    def publish(self):
        """Push the current values into profiler Counters (bounded: one
        sample per counter per call; the batcher calls this per batch)."""
        from .. import profiler
        snap = self.snapshot()
        keys = ["requests_total", "responses_ok", "shed_queue_full",
                "shed_deadline", "shed_total", "queue_depth",
                "batch_occupancy", "batches_total",
                "latency_p50_ms", "latency_p95_ms", "latency_p99_ms"]
        if snap["decode_streams_total"]:
            # decode families only on endpoints that actually decode, so
            # predict-only profiler tables stay exactly as before
            keys += ["decode_streams_total", "decode_tokens_total",
                     "decode_active", "kv_pages_live", "kv_page_occupancy",
                     "kv_pages_free", "kv_pages_used", "kv_pages_shared",
                     "ttft_p50_ms", "ttft_p99_ms",
                     "token_p50_ms", "token_p99_ms"]
            if snap["prefix_cache_hits"] or snap["prefix_cache_misses"]:
                keys += ["prefix_cache_hits", "prefix_cache_misses",
                         "prefix_tokens_saved"]
            if snap["kv_pages_imported_total"]:
                keys += ["kv_pages_imported_total"]
            if snap["spec_steps_total"]:
                # spec families only on schedulers that speculate, so
                # plain-decode profiler tables stay exactly as before
                keys += ["spec_steps_total", "spec_tokens_proposed_total",
                         "spec_tokens_accepted_total", "spec_adaptive_k",
                         "spec_accept_rate_mean",
                         "spec_draft_p50_ms", "spec_verify_p50_ms"]
        for key in keys:
            name = f"{self.name}:{key}"
            c = self._profiler_counters.get(name)
            if c is None:
                c = self._profiler_counters[name] = \
                    profiler.Counter(None, name)
            c.set_value(snap[key])
        self._warn_if_queue_bound()
        return snap

    def render_prometheus(self):
        """Prometheus text lines for the per-bucket queue/device latency
        split (appended to profiler.render_prometheus() at /metrics).

        Spec-conformant exposition: one HELP/TYPE per family with all of
        the family's samples contiguous, the quantile gauges kept as the
        cheap operator surface, a declared dispatches counter, and TRUE
        histogram families (cumulative `le` buckets ending at +Inf plus
        `_sum`/`_count`) so a scraper can do histogram_quantile() over
        any window instead of trusting our precomputed p50/p95."""
        buckets = self.bucket_snapshot()
        decode_seen = self.decode_streams_total > 0 or self.ttft.count > 0
        if not buckets and not decode_seen:
            return ""
        lines = []
        if buckets:
            with self._lock:
                pairs = sorted(self._bucket_hists.items())
            lines += ["# HELP mxnet_serve_bucket_latency_ms per-bucket "
                      "serving latency split: queue_wait vs device time",
                      "# TYPE mxnet_serve_bucket_latency_ms gauge"]
            for b, row in buckets.items():
                for kind in ("queue_wait", "device"):
                    for q in ("p50", "p95"):
                        lines.append(
                            f'mxnet_serve_bucket_latency_ms'
                            f'{{model="{self.name}"'
                            f',bucket="{b}",kind="{kind}",q="{q}"}} '
                            f'{row[f"{kind}_{q}_ms"]:.6g}')
            lines += ["# HELP mxnet_serve_bucket_dispatches batched "
                      "dispatches of each compiled bucket",
                      "# TYPE mxnet_serve_bucket_dispatches counter"]
            for b, row in buckets.items():
                lines.append(
                    f'mxnet_serve_bucket_dispatches{{model="{self.name}"'
                    f',bucket="{b}"}} {row["dispatches"]}')
            for kind, idx, help_text in (
                    ("queue_wait", 0,
                     "per-request wait for a bucket slot, in ms"),
                    ("device", 1,
                     "batched forward/device time per dispatch, in ms")):
                fam = f"mxnet_serve_bucket_{kind}_ms"
                lines += [f"# HELP {fam} {help_text}",
                          f"# TYPE {fam} histogram"]
                for b, hs in pairs:
                    state = hs[idx].snapshot_state()
                    labels = f'model="{self.name}",bucket="{b}"'
                    lines += self._histogram_lines(fam, labels, state)
        if decode_seen:
            lines += self._decode_prometheus_lines()
        return "\n".join(lines) + "\n"

    @staticmethod
    def _histogram_lines(fam, labels, state, scale=1e3):
        """Cumulative-`le` exposition for one LatencyHistogram state.
        ``scale`` converts the stored unit for the `le` bounds and sum
        (1e3: seconds -> ms; 1: dimensionless, e.g. accept fraction)."""
        lines = []
        cum = 0
        for bound, n in zip(state["bounds"], state["counts"]):
            cum += n
            lines.append(f'{fam}_bucket{{{labels},'
                         f'le="{bound * scale:.6g}"}} {cum}')
        cum += state["counts"][-1]
        lines.append(f'{fam}_bucket{{{labels},le="+Inf"}} {cum}')
        lines.append(f'{fam}_sum{{{labels}}} {state["sum"] * scale:.6g}')
        lines.append(f'{fam}_count{{{labels}}} {state["count"]}')
        return lines

    def _decode_prometheus_lines(self):
        """`mxnet_serve_decode_*` families: TTFT and inter-token true
        histograms plus the stream/token counters and KV-pool gauges a
        capacity dashboard needs."""
        labels = f'model="{self.name}"'
        lines = []
        for fam, h, help_text in (
                ("mxnet_serve_decode_ttft_ms", self.ttft,
                 "time to first token (submit -> prefill token), in ms"),
                ("mxnet_serve_decode_token_ms", self.token_latency,
                 "inter-token latency during decode, in ms")):
            lines += [f"# HELP {fam} {help_text}",
                      f"# TYPE {fam} histogram"]
            lines += self._histogram_lines(fam, labels, h.snapshot_state())
        for fam, val, kind, help_text in (
                ("mxnet_serve_decode_streams_total",
                 self.decode_streams_total, "counter",
                 "decode streams admitted"),
                ("mxnet_serve_decode_tokens_total",
                 self.decode_tokens_total, "counter",
                 "tokens delivered across all streams"),
                ("mxnet_serve_decode_active", self.decode_active, "gauge",
                 "occupied decode slots"),
                ("mxnet_serve_decode_kv_pages_live", self.kv_pages_live,
                 "gauge", "KV pages currently owned by live sequences"),
                ("mxnet_serve_decode_kv_pages_total", self.kv_pages_total,
                 "gauge", "KV page pool capacity"),
                # allocator occupancy triple: free + used == total, and
                # shared counts pages with refcount >= 2 (prefix-cache
                # overlap a capacity planner must NOT double-count)
                ("mxnet_kv_pages_free", self.kv_pages_free, "gauge",
                 "KV pages on the free list of this pool"),
                ("mxnet_kv_pages_used", self.kv_pages_used, "gauge",
                 "KV pages with at least one holder in this pool"),
                ("mxnet_kv_pages_shared", self.kv_pages_shared, "gauge",
                 "KV pages shared by multiple holders (CoW prefix reuse)"),
                ("mxnet_serve_prefix_cache_hits", self.prefix_cache_hits,
                 "counter", "prefix-cache lookups that reused pages"),
                ("mxnet_serve_prefix_tokens_saved",
                 self.prefix_tokens_saved, "counter",
                 "prompt tokens whose prefill was skipped via the cache")):
            lines += [f"# HELP {fam} {help_text}",
                      f"# TYPE {fam} {kind}",
                      f"{fam}{{{labels}}} {val}"]
        if self.spec_steps_total:
            lines += self._spec_prometheus_lines(labels)
        lines += reqtrace_exemplar_lines(self.ttft, labels, "decode_ttft")
        return lines

    def _spec_prometheus_lines(self, labels):
        """`mxnet_serve_spec_*` families (spec schedulers only): the
        accept-rate histogram (dimensionless `le` bounds), draft/verify
        time histograms, and the adaptive-k/throughput counters."""
        lines = []
        fam = "mxnet_serve_spec_accept_rate"
        lines += [f"# HELP {fam} per-stream per-step draft accept "
                  "fraction (0..1)",
                  f"# TYPE {fam} histogram"]
        lines += self._histogram_lines(
            fam, labels, self.spec_accept_rate.snapshot_state(), scale=1)
        for fam, h, help_text in (
                ("mxnet_serve_spec_draft_ms", self.spec_draft_time,
                 "host-side draft proposal time per iteration, in ms"),
                ("mxnet_serve_spec_verify_ms", self.spec_verify_time,
                 "batched verify dispatch time per iteration, in ms")):
            lines += [f"# HELP {fam} {help_text}",
                      f"# TYPE {fam} histogram"]
            lines += self._histogram_lines(fam, labels, h.snapshot_state())
        for fam, val, kind, help_text in (
                ("mxnet_serve_spec_steps_total", self.spec_steps_total,
                 "counter", "speculative draft+verify iterations"),
                ("mxnet_serve_spec_tokens_proposed_total",
                 self.spec_tokens_proposed_total, "counter",
                 "draft tokens offered to verify"),
                ("mxnet_serve_spec_tokens_accepted_total",
                 self.spec_tokens_accepted_total, "counter",
                 "draft tokens the target model agreed with"),
                ("mxnet_serve_spec_adaptive_k", self.spec_adaptive_k,
                 "gauge", "mean per-stream adaptive draft depth")):
            lines += [f"# HELP {fam} {help_text}",
                      f"# TYPE {fam} {kind}",
                      f"{fam}{{{labels}}} {val}"]
        return lines

    def table(self):
        snap = self.snapshot()
        width = max(len(k) for k in snap) + 2
        lines = [f"[{self.name}] serving stats", "-" * (width + 16)]
        for k, v in snap.items():
            lines.append(f"{k:<{width}}{v:>14}")
        return "\n".join(lines)
