"""Predict-only inference entry point (reference c_predict ABI).

Reference: include/mxnet/c_predict_api.h (350 LoC over
src/c_api/c_predict_api.cc): MXPredCreate(symbol json + .params payload),
MXPredSetInput, MXPredForward, MXPredGetOutput(Shape), MXPredReshape — a
deliberately tiny surface that needs no training runtime, so it can sit
in a serving binary.

TPU-native redesign: the NNVM graph executor becomes one cached `jax.jit`
executable per input-shape signature over the Symbol's functional
evaluator (`symbol._build_eval`, the same path `SymbolBlock` uses), with
parameters held on device and passed as traced arguments. Shape discipline
is the serving-critical part (Ragged Paged Attention, arXiv:2604.15464:
TPU serving wins come from a SMALL FIXED set of compiled bucket shapes):
a `bucket_sizes` ladder pads every batch up to the next bucket, so the
executable count is bounded by the ladder length — never by traffic.
Executables live in the process-wide two-tier cache (`compile_cache`):
`max_executables` is advisory — crossing it warns about unbucketed
traffic, and eviction is owned by the unified LRU
(`MXNET_EXEC_CACHE_SIZE`). With `MXNET_EXEC_CACHE_DIR` set, `warmup()`
(or `prewarm=True`) deserializes every bucket's executable ahead of first
traffic, so a fleet replica cold-starts without a single XLA retrace.
"""
from __future__ import annotations

import json as _json
import os
import threading

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray
from .. import mxsan as _mxsan

__all__ = ["Predictor", "BucketLadder"]


class BucketLadder:
    """A fixed ascending ladder of batch sizes; requests pad up to the
    smallest bucket that fits (one compiled executable per bucket)."""

    def __init__(self, sizes=(1, 2, 4, 8, 16, 32)):
        sizes = sorted({int(s) for s in sizes})
        if not sizes or sizes[0] < 1:
            raise MXNetError(f"invalid bucket ladder {sizes}")
        self.sizes = tuple(sizes)

    @property
    def max_size(self):
        return self.sizes[-1]

    def bucket_for(self, n):
        """Smallest bucket >= n, or None when n exceeds the ladder (the
        caller must split the batch)."""
        for s in self.sizes:
            if s >= n:
                return s
        return None

    def __len__(self):
        return len(self.sizes)

    def __repr__(self):
        return f"BucketLadder{self.sizes}"


def _strip_param_prefix(params):
    """Reference .params artifacts name entries `arg:w`/`aux:m`
    (module checkpoint convention, also written by HybridBlock.export)."""
    return {k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k: v
            for k, v in params.items()}


class Predictor:
    """Predict-only executor over an exported (symbol.json, .params) pair.

    Stateful surface (`set_input`/`forward`/`get_output`) mirrors the
    reference predictor one-to-one for porting ease; the stateless
    `predict(inputs)` is the thread-safe hot path the serving batcher
    uses — it touches no per-handle state, so any number of batcher and
    client threads can share one Predictor (XLA executables are
    reentrant).
    """

    def __init__(self, symbol, params=None, input_shapes=None, ctx=None,
                 bucket_sizes=(1, 2, 4, 8, 16, 32), max_executables=None,
                 batch_axis=0, prewarm=False):
        from .. import symbol as _sym
        from .. import nd

        # -- symbol: Symbol object, path to -symbol.json, or json text --
        if isinstance(symbol, str):
            if os.path.exists(symbol):
                symbol = _sym.load(symbol)
            elif symbol.lstrip().startswith("{"):
                symbol = _sym.load_json(symbol)
            else:
                raise MXNetError(f"no such symbol file: {symbol}")
        self._sym = symbol

        # -- params: dict, .params path, or raw container bytes ---------
        if params is None:
            params = {}
        elif isinstance(params, (bytes, bytearray)):
            params = nd.load_frombuffer(bytes(params))
        elif isinstance(params, str):
            params = nd.load(params)
        if not isinstance(params, dict):
            raise MXNetError(".params payload must be a name->NDArray map")
        params = _strip_param_prefix(params)

        args = list(self._sym.list_arguments())
        aux = list(self._sym.list_auxiliary_states())
        known = set(args) | set(aux)
        if input_shapes is not None:
            self._input_names = list(input_shapes)
            self._input_shapes = {k: tuple(v) if v is not None else None
                                  for k, v in dict(input_shapes).items()}
        else:
            self._input_names = [a for a in args if a not in params]
            self._input_shapes = {}
        missing = [a for a in args + aux
                   if a not in params and a not in self._input_names]
        if missing:
            raise MXNetError(
                f"graph inputs {missing[:5]} are neither in .params nor "
                f"declared as inputs {self._input_names}")
        unknown = [k for k in self._input_names if k not in known]
        if unknown:
            raise MXNetError(
                f"declared inputs {unknown} are not arguments of the "
                f"graph (arguments: {sorted(known)[:8]}...)")

        import jax
        dev = ctx.jax_device if ctx is not None else None
        self._param_vals = {}
        for name in args + aux:
            if name in params:
                v = params[name]
                a = v._data if isinstance(v, NDArray) else jax.numpy.asarray(v)
                self._param_vals[name] = (jax.device_put(a, dev)
                                          if dev is not None else a)

        self.ladder = (BucketLadder(bucket_sizes)
                       if bucket_sizes is not None else None)
        # advisory bound: one executable per bucket, or 16 for free-shape
        # use — crossing it warns (unbucketed-traffic bug) but no longer
        # hard-fails; the unified exec-cache LRU owns eviction
        self._max_executables = (max_executables if max_executables
                                 else (len(self.ladder) if self.ladder
                                       else 16))
        self._batch_axis = batch_axis
        self._executables = {}
        self._warm_buckets = set()      # buckets warmup() has realized
        self._cap_warned = False
        self._compile_lock = _mxsan.lock(
            "serve/predictor.py", "self._compile_lock")
        self._run = self._sym._build_eval(training=False)
        self._inputs = {}
        self._outputs = None
        if prewarm:
            self.warmup()

    # ------------------------------------------------------------------
    # compiled-executable management
    # ------------------------------------------------------------------
    @property
    def num_executables(self):
        return len(self._executables)

    @property
    def output_names(self):
        return self._sym.list_outputs()

    def _executable_for(self, sig):
        fn = self._executables.get(sig)
        if fn is not None:
            return fn
        with self._compile_lock:
            fn = self._executables.get(sig)
            if fn is not None:
                return fn
            if len(self._executables) >= self._max_executables and \
                    not self._cap_warned:
                # pre-unification this was a hard MXNetError; the unified
                # LRU makes an over-ladder signature cost one compile +
                # one eviction instead of an outage, but it is still the
                # unbucketed-traffic bug — say so once
                self._cap_warned = True
                import logging
                logging.warning(
                    "predictor: %d executable signatures exceed the "
                    "advisory cap %d (ladder %s) — traffic is compiling "
                    "outside the bucket ladder; the shared exec-cache "
                    "LRU (MXNET_EXEC_CACHE_SIZE) now owns eviction",
                    len(self._executables) + 1, self._max_executables,
                    self.ladder)

            run = self._run

            def call(param_vals, input_vals):
                outs, _ = run({**param_vals, **input_vals})
                return tuple(outs)

            from .. import compile_cache as _cc
            shapes = ",".join("x".join(map(str, shape))
                              for _, shape, _ in sig)
            fn = _cc.cached_jit(f"serve:exec[{shapes}]", call)
            self._executables[sig] = fn
            return fn

    def warmup(self, input_shapes=None, dtypes=None):
        """AOT pre-warm: materialize one executable per ladder bucket
        BEFORE first traffic, from abstract `jax.ShapeDtypeStruct` avals
        (no example batch needed). With a warm `MXNET_EXEC_CACHE_DIR`
        every bucket deserializes instead of compiling, so a fleet
        replica reaches first-prediction in milliseconds.

        input_shapes: per-input full shapes (batch axis value is ignored
            and swept over the ladder); defaults to the shapes declared
            at construction. dtypes: per-input dtype map or one dtype
            string for all inputs (default float32).

        Returns {bucket_size: "hit" | "disk" | "miss"} — a warm fleet
        sees "disk" everywhere."""
        import jax
        import jax.numpy as jnp

        shapes = dict(self._input_shapes)
        if input_shapes:
            shapes.update({k: tuple(v)
                           for k, v in dict(input_shapes).items()})
        missing = [k for k in self._input_names if not shapes.get(k)]
        if missing:
            raise MXNetError(
                f"warmup needs full input shapes for {missing}; declare "
                f"input_shapes at construction or pass them here")
        if dtypes is None:
            dtypes = {}
        elif isinstance(dtypes, str):
            dtypes = {k: dtypes for k in self._input_names}
        buckets = self.ladder.sizes if self.ladder else \
            tuple(sorted({shapes[k][self._batch_axis]
                          for k in self._input_names}))
        out = {}
        for b in buckets:
            avals = {}
            for name in self._input_names:
                shp = list(shapes[name])
                if len(shp) <= self._batch_axis:
                    raise MXNetError(
                        f"input {name!r} shape {tuple(shp)} has no batch "
                        f"axis {self._batch_axis}")
                if self.ladder is not None:
                    shp[self._batch_axis] = b
                dt = jnp.dtype(dtypes.get(name, "float32"))
                avals[name] = jax.ShapeDtypeStruct(tuple(shp), dt)
            sig = tuple((name, tuple(a.shape), str(a.dtype))
                        for name, a in sorted(avals.items()))
            fn = self._executable_for(sig)
            out[b] = fn.warmup(self._param_vals, avals)
        self._warm_buckets.update(out)
        return out

    @property
    def is_warm(self):
        """True once warmup() has materialized an executable for every
        ladder bucket — the readiness gate the serving control plane
        consults: a replica advertises ready only when a request for any
        bucket runs without an XLA trace."""
        if self.ladder is None:
            return bool(self._warm_buckets)
        return set(self.ladder.sizes) <= self._warm_buckets

    def _pad_batch(self, arrays):
        """Pad dict of batched host/device arrays up the bucket ladder.
        Returns (padded, real_n). Padding rows are zeros; row independence
        of inference graphs makes them inert, and the exactness of the
        real rows is enforced by tests/test_serve.py."""
        n = None
        for name, a in arrays.items():
            if a.ndim <= self._batch_axis:
                raise MXNetError(f"input {name!r} has no batch axis")
            bn = a.shape[self._batch_axis]
            if n is None:
                n = bn
            elif bn != n:
                raise MXNetError(
                    f"inconsistent batch sizes across inputs ({bn} vs {n})")
        if n is None:
            raise MXNetError("no inputs bound")
        if self.ladder is None:
            return arrays, n
        bucket = self.ladder.bucket_for(n)
        if bucket is None:
            raise MXNetError(
                f"batch {n} exceeds the bucket ladder max "
                f"{self.ladder.max_size}; split the request")
        if bucket == n:
            return arrays, n
        padded = {}
        for name, a in arrays.items():
            widths = [(0, 0)] * a.ndim
            widths[self._batch_axis] = (0, bucket - n)
            padded[name] = _np.pad(_np.asarray(a), widths)
        return padded, n

    # ------------------------------------------------------------------
    # stateless hot path (used by the batcher/server)
    # ------------------------------------------------------------------
    def predict(self, inputs):
        """Run one batched forward: name->array (numpy or jax, batch on
        `batch_axis`) -> list of jax arrays sliced back to the real batch.
        Pure function of its arguments — safe from many threads."""
        import jax.numpy as jnp

        arrays = {}
        for name in self._input_names:
            if name not in inputs:
                raise MXNetError(f"missing input {name!r}")
            a = inputs[name]
            a = a._data if isinstance(a, NDArray) else _np.asarray(a)
            arrays[name] = a
        extra = set(inputs) - set(self._input_names)
        if extra:
            raise MXNetError(f"unknown inputs {sorted(extra)}")
        padded, n = self._pad_batch(arrays)
        sig = tuple((name, tuple(a.shape), str(a.dtype))
                    for name, a in sorted(padded.items()))
        fn = self._executable_for(sig)
        outs = fn(self._param_vals,
                  {k: jnp.asarray(v) for k, v in padded.items()})
        sliced = []
        for o in outs:
            if o.ndim > self._batch_axis and \
                    o.shape[self._batch_axis] != n:
                idx = [slice(None)] * o.ndim
                idx[self._batch_axis] = slice(0, n)
                o = o[tuple(idx)]
            sliced.append(o)
        return sliced

    # ------------------------------------------------------------------
    # reference c_predict stateful surface
    # ------------------------------------------------------------------
    def set_input(self, key, value):
        """MXPredSetInput."""
        if key not in self._input_names:
            raise MXNetError(
                f"unknown input {key!r} (inputs: {self._input_names})")
        value = value.asnumpy() if isinstance(value, NDArray) \
            else _np.asarray(value)
        want = self._input_shapes.get(key)
        if want is not None and tuple(value.shape) != tuple(want):
            raise MXNetError(
                f"input {key!r} shape {value.shape} != declared {want} "
                "(use reshape() to change the signature)")
        self._inputs[key] = value
        return self

    def forward(self, **kwargs):
        """MXPredForward; keyword inputs are a set_input shorthand."""
        for k, v in kwargs.items():
            self.set_input(k, v)
        missing = [k for k in self._input_names if k not in self._inputs]
        if missing:
            raise MXNetError(f"inputs not set: {missing}")
        self._outputs = self.predict(self._inputs)
        return self

    def get_output(self, index=0):
        """MXPredGetOutput -> NDArray."""
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return NDArray(self._outputs[index])

    def get_output_shape(self, index=0):
        """MXPredGetOutputShape."""
        if self._outputs is None:
            raise MXNetError("call forward() before get_output_shape()")
        return tuple(self._outputs[index].shape)

    def reshape(self, new_input_shapes):
        """MXPredReshape: re-declare the input signature. Executables are
        per-shape already, so this just validates + clears bound state;
        the reference returned a new handle for the same reason."""
        self._input_shapes.update({k: tuple(v) for k, v
                                   in dict(new_input_shapes).items()})
        self._inputs.clear()
        self._outputs = None
        return self

    # ------------------------------------------------------------------
    @classmethod
    def from_artifact(cls, prefix, epoch=0, **kwargs):
        """Load a `HybridBlock.export` / `Module.save_checkpoint` artifact
        pair `{prefix}-symbol.json` + `{prefix}-{epoch:04d}.params`."""
        return cls(f"{prefix}-symbol.json",
                   f"{prefix}-{epoch:04d}.params", **kwargs)

    def __repr__(self):
        return (f"Predictor(inputs={self._input_names}, "
                f"outputs={len(self._sym.list_outputs())}, "
                f"ladder={self.ladder}, "
                f"executables={self.num_executables})")
