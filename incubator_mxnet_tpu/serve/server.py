"""Threaded HTTP front end for a Predictor — stdlib only.

The reference never shipped a server (c_predict was embedded into user
binaries); the north star ("serve heavy traffic") needs one. This is a
deliberately small threaded front end over the DynamicBatcher: admission
control lives in the batcher's bounded queue, and the server's job is to
map the serving protocol onto HTTP honestly:

  200  result
  503  Overloaded       (queue full)           Retry-After + retryable:true
  504  DeadlineExceeded (expired in queue/wait)            retryable:true
  400  malformed request                                   retryable:false
  500  predict raised                                      retryable:false

A saturating burst therefore degrades into fast 503s (clients retry
elsewhere/later) instead of collapsing into unbounded queueing — the same
shed-don't-stall policy the kvstore server and fault.py use.

Protocol (JSON):
  POST /predict   {"inputs": {"data": [[...]]}, "deadline_ms": 250}
                  -> {"outputs": [[...], ...]}   (one list per output,
                     sample-shaped — requests are UNBATCHED samples)
  POST /generate  {"prompt": [ids...], "max_new_tokens": n,
                   "stream": true, "deadline_ms": 5000}
                  -> chunked application/x-ndjson, one {"token": id}
                     line flushed PER TOKEN as the decode scheduler
                     emits it, terminated by a {"done": true, ...}
                     summary line (or {"error": ...} mid-stream);
                     "stream": false buffers and answers one JSON
                     {"tokens": [...]}. Requires a DecodeScheduler
                     attached via ModelServer(decoder=...); sheds
                     exactly like /predict (503 Overloaded + Retry-After
                     when the queue or the KV page pool is saturated,
                     504 on deadline, fast 503 while draining).
  GET  /healthz   -> LIVENESS: 200 {"status": "ok", ...} while the
                     process serves at all (a draining replica is alive)
  GET  /readyz    -> READINESS: 200 only when the replica should take
                     traffic — every ladder bucket AOT-warm
                     (Predictor.warmup completed), registered with the
                     control plane (when one is attached), and not
                     draining; otherwise 503 naming each failing gate
  GET  /stats     -> ServingStats.snapshot()
  GET  /metrics   -> Prometheus text exposition (serving counters +
                     trainer counters + compile-cache + memory gauges,
                     profiler.render_prometheus())

Control-plane admin surface (loopback-bound by default; see
docs/architecture/note_control_plane.md for the trust model):
  POST /admin/reload    {"params": path, "generation": g} — prewarm the
                        new generation from the disk cache, drain the
                        old through the batcher's admission control,
                        swap, resume (the zero-downtime weight shift)
  POST /admin/rollback  — swap back to the retained previous generation
  POST /admin/drain     — begin drain (deregister + shed new requests)

Graceful shutdown: ``install_sigterm()`` turns SIGTERM into
deregister -> 503 + Retry-After for new requests -> drain in-flight ->
flush stats -> stop, instead of the stdlib server dying mid-batch.
"""
from __future__ import annotations

import json
import signal
import threading
import time
from concurrent.futures import TimeoutError as _FutTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from ..base import MXNetError
from ..util import getenv_int, getenv_str
from . import reqtrace as _rt
from .batcher import DeadlineExceeded, DynamicBatcher, Overloaded
from .stats import ServingStats
from .. import mxsan as _mxsan

__all__ = ["ModelServer"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mxtpu-serve/0.1"

    # the ModelServer instance is attached to the socket server
    @property
    def _ms(self):
        return self.server.model_server

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, code, payload, retry_after=None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code, text, content_type="text/plain"):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        ms = self._ms
        if self.path == "/healthz":
            # liveness ONLY: a draining or cold replica is still alive —
            # orchestrators must not restart it for being unready
            self._reply(200, {"status": "ok",
                              "queue_depth": ms.stats.queue_depth,
                              "draining": ms.draining,
                              "generation": ms.generation})
        elif self.path == "/readyz":
            ready, why = ms.readiness()
            self._reply(200 if ready else 503,
                        {"ready": ready, "why": why,
                         "generation": ms.generation})
        elif self.path == "/stats":
            snap = ms.stats.snapshot()
            if ms.decoder is not None and ms.decoder.stats is not ms.stats:
                snap["decode"] = ms.decoder.stats.snapshot()
            self._reply(200, snap)
        elif self.path == "/metrics":
            from .. import profiler
            # refresh this endpoint's serving counters so a scrape always
            # sees current values regardless of batch cadence
            ms.stats.publish()
            body = (profiler.render_prometheus()
                    + ms.stats.render_prometheus())
            if ms.decoder is not None and ms.decoder.stats is not ms.stats:
                ms.decoder.stats.publish()
                body += ms.decoder.stats.render_prometheus()
            body += _rt.render_prometheus(f'model="{ms.stats.name}"')
            self._reply_text(
                200, body,
                content_type="text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/debugz/requests":
            # this process's request-trace rings (recent sampled requests
            # + error/SLO-breach exemplars); empty when MXNET_REQTRACE off
            self._reply(200, _rt.ring_snapshot())
        else:
            self._reply(404, {"error": "not found", "retryable": False})

    def do_POST(self):
        if self.path.startswith("/admin/"):
            self._admin()
            return
        if self.path == "/generate":
            self._generate()
            return
        if self.path == "/prefill":
            self._prefill()
            return
        if self.path != "/predict":
            self._reply(404, {"error": "not found", "retryable": False})
            return
        ms = self._ms
        if ms.draining:
            # graceful-shutdown / rollout contract: a draining replica
            # answers fast with a retryable shed, never queues
            self._reply(503, {"error": "draining", "retryable": True},
                        retry_after="0.1")
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            raw = req["inputs"]
            inputs = {k: _np.asarray(v, dtype=_np.float32)
                      for k, v in raw.items()}
            deadline_ms = req.get("deadline_ms", ms.default_deadline_ms)
        except (KeyError, ValueError, TypeError) as e:
            self._reply(400, {"error": f"malformed request: {e}",
                              "retryable": False})
            return
        try:
            fut = ms.batcher.submit(inputs, deadline_ms=deadline_ms)
            timeout = (deadline_ms / 1e3 + 1.0) if deadline_ms else None
            outs = fut.result(timeout=timeout)
        except Overloaded as e:
            self._reply(e.status, {"error": str(e), "retryable": True},
                        retry_after="0.05")
            return
        except (DeadlineExceeded, _FutTimeout) as e:
            self._reply(504, {"error": str(e) or "deadline exceeded",
                              "retryable": True})
            return
        except Exception as e:  # noqa: BLE001 — predict failure -> 500
            self._reply(500, {"error": str(e), "retryable": False})
            return
        self._reply(200, {"outputs": [o.tolist() for o in outs]})

    def _prefill(self):
        """Prefill-role endpoint: run chunked prefill, export the KV
        pages, and (default) ship them to the coordinator's page store
        under the request's ship_key — the decode replica's /generate
        fetches them by that key. ``ship: false`` returns the rows
        inline (coordinator-less tests/tools)."""
        ms = self._ms
        if ms.prefill_engine is None:
            self._reply(404, {"error": "no prefill engine attached "
                              "(replica role is not prefill-capable)",
                              "retryable": False})
            return
        if ms.draining:
            self._reply(503, {"error": "draining", "retryable": True},
                        retry_after="0.1")
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            prompt = [int(t) for t in req["prompt"]]
            ship = bool(req.get("ship", ms.coordinator is not None))
            ship_key = req.get("ship_key")
        except (KeyError, ValueError, TypeError) as e:
            self._reply(400, {"error": f"malformed request: {e}",
                              "retryable": False})
            return
        # request tracing: adopt the router-minted context so the
        # prefill_chunk/kv_ship spans and the kvstore wire carry its id,
        # and return the measured legs for the TTFT budget breakdown
        ctx = _rt.from_header(self.headers.get(_rt.TRACE_HEADER))
        t_run = time.perf_counter()
        try:
            with _rt.activate(ctx):
                export = ms.prefill_engine.run(prompt)
        except Overloaded as e:
            self._reply(e.status, {"error": str(e), "retryable": True},
                        retry_after="0.05")
            return
        except MXNetError as e:
            self._reply(400, {"error": str(e), "retryable": False})
            return
        prefill_ms = (time.perf_counter() - t_run) * 1e3
        ship_ms = 0.0
        out = {"next_token": export["next_token"], "n": export["n"],
               "cached_tokens": export["cached_tokens"],
               "pages": len(export["k_rows"])}
        if ship:
            if ms.coordinator is None:
                self._reply(400, {"error": "ship requested but no "
                                  "coordinator attached", "retryable": False})
                return
            if not ship_key:
                self._reply(400, {"error": "ship requested without "
                                  "ship_key", "retryable": False})
                return
            t_ship = time.perf_counter()
            try:
                with _rt.activate(ctx):
                    receipt = ms.ship_export(ship_key, export)
            except MXNetError as e:
                self._reply(503, {"error": f"page shipping failed: {e}",
                                  "retryable": True}, retry_after="0.05")
                return
            ship_ms = (time.perf_counter() - t_ship) * 1e3
            out["ship_key"] = ship_key
            out["shipped_bytes"] = int(receipt.get("bytes", 0))
        else:
            out["k_rows"] = export["k_rows"].tolist()
            out["v_rows"] = export["v_rows"].tolist()
        if ctx is not None:
            out["prefill_ms"] = round(prefill_ms, 3)
            out["ship_ms"] = round(ship_ms, 3)
        self._reply(200, out)

    def _generate(self):
        ms = self._ms
        if ms.decoder is None:
            self._reply(404, {"error": "no decoder attached",
                              "retryable": False})
            return
        if ms.draining:
            self._reply(503, {"error": "draining", "retryable": True},
                        retry_after="0.1")
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            prompt = [int(t) for t in req["prompt"]]
            max_new = req.get("max_new_tokens")
            eos_id = req.get("eos_id")
            stream_mode = bool(req.get("stream", True))
            deadline_ms = req.get("deadline_ms", ms.default_deadline_ms)
            ship_key = req.get("ship_key")
            kv_inline = req.get("kv_import")
        except (KeyError, ValueError, TypeError) as e:
            self._reply(400, {"error": f"malformed request: {e}",
                              "retryable": False})
            return
        # request tracing: adopt the router-minted context; the decode
        # stream carries it so the scheduler can book admission spans and
        # assemble the done-row TTFT budget breakdown
        ctx = _rt.from_header(self.headers.get(_rt.TRACE_HEADER))
        kv_import = None
        if kv_inline is not None:
            kv_import = kv_inline
        elif ship_key:
            # fetch the prefill replica's exported pages; an expired or
            # unknown key falls back to local prefill (when the prompt
            # fits this replica's ladder)
            with _rt.activate(ctx):
                kv_import = ms.fetch_shipped(ship_key)
        try:
            st = ms.decoder.submit(prompt, max_new_tokens=max_new,
                                   eos_id=eos_id, deadline_ms=deadline_ms,
                                   kv_import=kv_import, trace=ctx)
        except Overloaded as e:
            self._reply(e.status, {"error": str(e), "retryable": True},
                        retry_after="0.05")
            return
        except DeadlineExceeded as e:
            self._reply(504, {"error": str(e), "retryable": True})
            return
        except MXNetError as e:
            self._reply(400, {"error": str(e), "retryable": False})
            return
        if not stream_mode:
            try:
                timeout = (deadline_ms / 1e3 + 5.0) if deadline_ms else None
                toks = st.result(timeout=timeout)
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e), "retryable": True})
                return
            except Exception as e:  # noqa: BLE001 — decode failure -> 500
                self._reply(500, {"error": str(e), "retryable": False})
                return
            payload = {"tokens": toks, "ttft_ms": st.ttft_ms}
            if ctx is not None:
                payload["budget"] = self._budget_row(ctx, st)
                _rt.finish(ctx, status="ok", ttft_ms=st.ttft_ms,
                           budget=payload["budget"])
            self._reply(200, payload)
            return
        # chunked streaming: one ndjson line per token, flushed as the
        # scheduler emits it — the client sees its first token at TTFT,
        # not at stream completion
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(obj):
            data = (json.dumps(obj) + "\n").encode("utf-8")
            self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
            self.wfile.flush()

        try:
            try:
                for tok in st:
                    chunk({"token": tok})
                done_row = {"done": True, "n": len(st._tokens),
                            "ttft_ms": st.ttft_ms}
                if ctx is not None:
                    # TTFT budget breakdown: router-side legs from the
                    # header baggage + scheduler-measured components; the
                    # row only exists on traced requests, so the gate-off
                    # stream stays byte-identical
                    done_row["budget"] = self._budget_row(ctx, st)
                    _rt.finish(ctx, status="ok", ttft_ms=st.ttft_ms,
                               budget=done_row["budget"])
                chunk(done_row)
            except MXNetError as e:
                # the chunked response already started: the error must
                # travel in-band as the final line
                chunk({"error": str(e),
                       "retryable": bool(getattr(e, "retryable", False))})
                _rt.finish(ctx, status="error", cause=type(e).__name__,
                           ttft_ms=st.ttft_ms)
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except OSError:
            # client went away mid-stream: stop generating for it
            st.cancel()
            self.close_connection = True

    @staticmethod
    def _budget_row(ctx, st):
        """Assemble the done-row TTFT budget: router_ms/prefill_ms/
        ship_ms ride in as header baggage from the router, queue_ms/
        admission_ms/first_step_ms are measured by the decode scheduler
        (DecodeStream._budget). The six components sum to the measured
        TTFT within scheduling tolerance."""
        budget = {"router_ms": 0.0, "prefill_ms": 0.0, "ship_ms": 0.0,
                  "queue_ms": 0.0, "admission_ms": 0.0,
                  "first_step_ms": 0.0}
        for leg in ("router_ms", "prefill_ms", "ship_ms"):
            try:
                budget[leg] = round(float(ctx.baggage.get(leg, 0.0)), 3)
            except (TypeError, ValueError):
                pass
        budget.update(getattr(st, "_budget", None) or {})
        return budget

    def _admin(self):
        ms = self._ms
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": f"malformed request: {e}",
                              "retryable": False})
            return
        try:
            if self.path == "/admin/reload":
                out = ms.reload(req["params"], int(req["generation"]))
            elif self.path == "/admin/rollback":
                out = ms.rollback()
            elif self.path == "/admin/drain":
                ms.begin_drain(reason=req.get("reason", "admin"))
                out = {"draining": True}
            else:
                self._reply(404, {"error": "not found", "retryable": False})
                return
        except KeyError as e:
            self._reply(400, {"error": f"missing field {e}",
                              "retryable": False})
            return
        except Exception as e:      # noqa: BLE001 — admin failure -> 500
            self._reply(500, {"error": str(e), "retryable": False})
            return
        self._reply(200, out)


class _HTTPServer(ThreadingHTTPServer):
    # accept backlog must exceed the admission queue: shedding is the
    # batcher's job (fast 503), not the kernel's (silent RST under bursts)
    request_queue_size = 256
    daemon_threads = True


class ModelServer:
    """Serve a Predictor over HTTP with dynamic batching + admission
    control. `port=0` binds an ephemeral port (returned by start()).

    Control-plane integration (all optional — a bare ModelServer keeps
    the original single-process behavior):

    model / generation:  identity advertised to the serve registry.
    coordinator:         "addr token" of the kvstore coordinator; when
                         set, start() registers a ReplicaAgent that
                         heartbeats (generation, ready, draining) and
                         stop()/drain deregisters.
    require_warm:        readiness gate on Predictor.warmup having
                         realized every ladder bucket. None (default)
                         auto-enables when the predictor declares input
                         shapes (i.e. warmup is possible).
    decoder:             optional DecodeScheduler; attaches the
                         streaming /generate endpoint, adds its warmth
                         to the readiness gate, and ties its admission
                         control into drain/rollout (pause + quiesce
                         alongside the batcher, so PR-12 semantics cover
                         decode streams too).
    role:                disaggregated-serving role advertised to the
                         registry: "prefill" (serves /prefill, ships KV
                         pages), "decode" (serves /generate, imports
                         shipped pages via ship_key), or "both"
                         (default, PR-13 colocated behavior). Defaults
                         to $MXNET_DISAGG_ROLE.
    prefill_engine:      optional disagg.PrefillEngine; attaches the
                         /prefill endpoint and its warmth to readiness.
    """

    def __init__(self, predictor, host="127.0.0.1", port=0,
                 max_latency_ms=5.0, max_queue=128,
                 default_deadline_ms=1000.0, stats=None, name="serve",
                 model="default", generation=0, coordinator=None,
                 require_warm=None, decoder=None, role=None,
                 prefill_engine=None):
        self.predictor = predictor
        buckets = (predictor.ladder.sizes if predictor.ladder is not None
                   else (1, 2, 4, 8, 16, 32))
        self.stats = stats if stats is not None else ServingStats(name)
        self.batcher = DynamicBatcher(
            predictor.predict, buckets=buckets,
            max_latency_ms=max_latency_ms, max_queue=max_queue,
            default_deadline_ms=default_deadline_ms, stats=self.stats)
        self.default_deadline_ms = default_deadline_ms
        self.model = model
        self.generation = int(generation)
        self._coordinator = coordinator
        if require_warm is None:
            require_warm = (predictor.ladder is not None
                            and bool(predictor._input_shapes))
        self._require_warm = require_warm
        self.decoder = decoder
        if role is None:
            role = getenv_str("MXNET_DISAGG_ROLE")
        if role not in ("prefill", "decode", "both"):
            raise MXNetError(f"invalid disagg role {role!r} "
                             "(want prefill|decode|both)")
        self.role = role
        self.prefill_engine = prefill_engine
        self._ship_client = None        # lazy kvstore client for paging
        self._ship_lock = _mxsan.lock("serve/server.py", "self._ship_lock")
        self._host, self._port = host, port
        self._httpd = None
        self._thread = None
        self._agent = None
        self._draining = False
        self._drain_lock = _mxsan.lock(
            "serve/server.py", "self._drain_lock")     # serializes drain/swap
        self._prev = None       # (predictor, generation) for rollback
        self._prev_sigterm = None

    # -- health/readiness ----------------------------------------------
    @property
    def draining(self):
        return self._draining

    @property
    def buckets(self):
        return self.batcher._buckets

    def readiness(self):
        """(ready, why): the composite readiness gate /readyz serves and
        the ReplicaAgent beats to the registry — one truth for the
        router, the orchestrator, and the control plane."""
        why = []
        if self._httpd is None:
            why.append("not started")
        if self._draining:
            why.append("draining")
        if self._require_warm and not self.predictor.is_warm:
            why.append("cold buckets (Predictor.warmup incomplete)")
        if self.decoder is not None and not self.decoder.predictor.is_warm:
            why.append("cold decode executables "
                       "(DecodePredictor.warmup incomplete)")
        if self.decoder is not None and self.decoder.spec is not None \
                and not self.decoder.spec.is_warm:
            why.append("cold speculative verify executable "
                       "(SpecDecoder.warmup incomplete)")
        if self.prefill_engine is not None \
                and not self.prefill_engine.is_warm:
            why.append("cold prefill-chunk executable "
                       "(PrefillPredictor.warmup incomplete)")
        if self._coordinator is not None and (
                self._agent is None or not self._agent.registered):
            why.append("not registered with control plane")
        return (not why, why)

    @property
    def ready(self):
        return self.readiness()[0]

    @property
    def coordinator(self):
        return self._coordinator

    # -- disaggregated serving ------------------------------------------
    def _page_client(self):
        """Lazy authenticated kvstore client to the coordinator, shared
        by page shipping (prefill role) and fetching (decode role)."""
        if self._coordinator is None:
            raise MXNetError("no coordinator attached for page shipping")
        with self._ship_lock:
            if self._ship_client is None:
                from ..kvstore_server import connect_async_server
                self._ship_client = connect_async_server(self._coordinator)
            return self._ship_client

    def ship_export(self, ship_key, export):
        """Prefill role: push one export bundle to the coordinator's
        page store (kvstore.ship_kv_pages over the MAC'd wire)."""
        if self.prefill_engine is None:
            raise MXNetError("no prefill engine attached")
        return self.prefill_engine.ship(self._page_client(), ship_key,
                                        export)

    def fetch_shipped(self, ship_key):
        """Decode role: resolve a request's ship_key into a kv_import
        dict (or None on an unknown/expired key — the scheduler then
        prefills locally). The fetch is non-destructive so a whole-
        stream router retry can re-fetch the same key; TTL expiry on
        the coordinator garbage-collects it."""
        if self._coordinator is None:
            return None
        from . import disagg as _disagg
        try:
            return _disagg.fetch_kv_import(self._page_client(), ship_key)
        except MXNetError:
            return None

    def load_report(self):
        """Per-beat load snapshot the ReplicaAgent sends as the v2
        serve_beat payload — the router's decode-placement signal."""
        load = {"queue_depth": self.stats.queue_depth, "role": self.role}
        alloc = None
        if self.decoder is not None:
            alloc = self.decoder.allocator
            load["active_streams"] = self.decoder.active_streams
            # SLO headroom signals for the router's split placement
            # policy (MXNET_ROUTER_SLO_SPLIT): observed tail latencies
            # per role. Zero until the first streams complete — the
            # router treats missing/zero as "no evidence", not "fast".
            ds = self.decoder.stats
            if ds.ttft.count:
                load["ttft_p99_ms"] = round(ds.ttft.percentile(99) * 1e3, 3)
            if ds.token_latency.count:
                load["token_p99_ms"] = round(
                    ds.token_latency.percentile(99) * 1e3, 3)
        elif self.prefill_engine is not None:
            alloc = self.prefill_engine.allocator
            ps = self.prefill_engine.stats
            if ps.prefill_time.count:
                load["prefill_p99_ms"] = round(
                    ps.prefill_time.percentile(99) * 1e3, 3)
        if alloc is not None:
            load["kv_pages_free"] = alloc.free_count
            load["kv_pages_total"] = alloc.num_pages
        return load

    @property
    def address(self):
        if self._httpd is None:
            raise MXNetError("server not started")
        return self._httpd.server_address[:2]

    # -- lifecycle ------------------------------------------------------
    def start(self):
        if self._httpd is not None:
            return self.address
        self.batcher.start()
        if self.decoder is not None:
            self.decoder.start()
        self._httpd = _HTTPServer((self._host, self._port), _Handler)
        self._httpd.model_server = self
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="mxtpu-serve-http",
                                        daemon=True)
        self._thread.start()
        if self._coordinator is not None:
            from .control_plane import ReplicaAgent
            self._agent = ReplicaAgent(self, self._coordinator,
                                       model=self.model)
            try:
                self._agent.start()
            except MXNetError:
                self.stop()
                raise
        return self.address

    def stop(self):
        if self._agent is not None:
            self._agent.stop(deregister=True)
            self._agent = None
        with self._ship_lock:
            if self._ship_client is not None:
                try:
                    self._ship_client.close()
                except OSError:
                    pass
                self._ship_client = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.batcher.stop()
        if self.decoder is not None:
            self.decoder.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- graceful shutdown / drain -------------------------------------
    def begin_drain(self, reason="shutdown"):
        """Stop taking traffic without dropping anything in flight:
        deregister (routers stop picking us within one refresh), shed
        new requests with retryable 503 + Retry-After, flush the
        batcher's queue, publish final stats. Idempotent."""
        with self._drain_lock:
            if self._draining:
                return
            self._draining = True
        from .. import fault as _fault
        if self._agent is not None:
            self._agent.stop(deregister=True)
        self.batcher.pause(reason)
        drained = self.batcher.quiesce(
            timeout=getenv_int("MXNET_SERVE_DRAIN_TIMEOUT"))
        if self.decoder is not None:
            # same admission contract for streams: shed new generations
            # with retryable 503s, let in-flight streams run to their
            # last token before the replica goes away
            self.decoder.pause(reason)
            drained = self.decoder.quiesce(
                timeout=getenv_int("MXNET_SERVE_DRAIN_TIMEOUT")) and drained
        self.stats.publish()
        _fault.flight_record("serve_drain", model=self.model,
                             generation=self.generation, reason=reason,
                             drained=drained)
        from . import control_plane as _cp
        _cp._bump("graceful_shutdowns")

    def shutdown_gracefully(self, reason="sigterm"):
        self.begin_drain(reason=reason)
        self.stop()

    def install_sigterm(self):
        """Route SIGTERM through the graceful drain (main thread only).
        The handler only sets work in motion on a helper thread — signal
        context is no place for socket teardown. Returns self;
        restore_sigterm() undoes it (tests)."""
        def _on_term(signum, frame):
            threading.Thread(target=self.shutdown_gracefully,
                             name="mxtpu-serve-sigterm",
                             daemon=True).start()
        self._prev_sigterm = signal.signal(signal.SIGTERM, _on_term)
        return self

    def restore_sigterm(self):
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    # -- zero-downtime weight rollout ----------------------------------
    def reload(self, params, generation):
        """Shift this replica to a new generation with zero failed
        requests: build + AOT-prewarm the new Predictor from the disk
        cache while the old one serves, then drain the old generation
        through admission control (pause -> quiesce), swap, resume.
        The displaced generation is retained for rollback()."""
        from .predictor import Predictor
        pred = self.predictor
        new_pred = Predictor(
            pred._sym, params,
            input_shapes=(pred._input_shapes or None),
            bucket_sizes=(pred.ladder.sizes if pred.ladder else None),
            batch_axis=pred._batch_axis)
        warm = (new_pred.warmup() if self._require_warm else {})
        cold = sorted(b for b, v in warm.items() if v == "miss")
        info = self._swap(new_pred, generation, reason="reload")
        info["warmup"] = {str(b): v for b, v in warm.items()}
        info["cold_buckets"] = cold
        return info

    def rollback(self):
        """Swap back to the generation reload() displaced."""
        if self._prev is None:
            raise MXNetError("no previous generation retained")
        old_pred, old_gen = self._prev
        return self._swap(old_pred, old_gen, reason="rollback")

    def _swap(self, new_pred, generation, reason):
        from .. import fault as _fault
        t0 = time.monotonic()
        with self._drain_lock:
            if self._draining:
                raise MXNetError(f"cannot {reason}: replica is draining")
            # the drain window: requests arriving now get retryable 503s
            # (the router reroutes); everything already admitted flushes
            # on the OLD generation before the swap
            self._draining = True
            self.batcher.pause(f"{reason} gen {generation}")
            drained = self.batcher.quiesce(
                timeout=getenv_int("MXNET_SERVE_DRAIN_TIMEOUT"))
            if self.decoder is not None:
                # in-flight streams belong to the old generation: flush
                # them through the same admission gate before the swap
                self.decoder.pause(f"{reason} gen {generation}")
                drained = self.decoder.quiesce(
                    timeout=getenv_int("MXNET_SERVE_DRAIN_TIMEOUT")) \
                    and drained
            self._prev = (self.predictor, self.generation)
            self.predictor = new_pred
            self.batcher.swap_predict(new_pred.predict)
            old_gen, self.generation = self.generation, int(generation)
            self.batcher.resume()
            if self.decoder is not None:
                self.decoder.resume()
            self._draining = False
        swap_ms = (time.monotonic() - t0) * 1e3
        _fault.flight_record("serve_swap", model=self.model,
                             reason=reason, generation=int(generation),
                             previous=old_gen, drained=drained,
                             swap_ms=round(swap_ms, 3))
        if self._agent is not None:
            try:
                # readiness + generation reach the registry now, not at
                # the next beat period
                self._agent.beat_now()
            except MXNetError:
                pass
        return {"generation": self.generation, "previous": old_gen,
                "drained": drained, "swap_ms": swap_ms}
