"""Threaded HTTP front end for a Predictor — stdlib only.

The reference never shipped a server (c_predict was embedded into user
binaries); the north star ("serve heavy traffic") needs one. This is a
deliberately small threaded front end over the DynamicBatcher: admission
control lives in the batcher's bounded queue, and the server's job is to
map the serving protocol onto HTTP honestly:

  200  result
  503  Overloaded       (queue full)           Retry-After + retryable:true
  504  DeadlineExceeded (expired in queue/wait)            retryable:true
  400  malformed request                                   retryable:false
  500  predict raised                                      retryable:false

A saturating burst therefore degrades into fast 503s (clients retry
elsewhere/later) instead of collapsing into unbounded queueing — the same
shed-don't-stall policy the kvstore server and fault.py use.

Protocol (JSON):
  POST /predict   {"inputs": {"data": [[...]]}, "deadline_ms": 250}
                  -> {"outputs": [[...], ...]}   (one list per output,
                     sample-shaped — requests are UNBATCHED samples)
  GET  /healthz   -> {"status": "ok", "queue_depth": n}
  GET  /stats     -> ServingStats.snapshot()
  GET  /metrics   -> Prometheus text exposition (serving counters +
                     trainer counters + compile-cache + memory gauges,
                     profiler.render_prometheus())
"""
from __future__ import annotations

import json
import threading
from concurrent.futures import TimeoutError as _FutTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from ..base import MXNetError
from .batcher import DeadlineExceeded, DynamicBatcher, Overloaded
from .stats import ServingStats

__all__ = ["ModelServer"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mxtpu-serve/0.1"

    # the ModelServer instance is attached to the socket server
    @property
    def _ms(self):
        return self.server.model_server

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, code, payload, retry_after=None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code, text, content_type="text/plain"):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        ms = self._ms
        if self.path == "/healthz":
            self._reply(200, {"status": "ok",
                              "queue_depth": ms.stats.queue_depth})
        elif self.path == "/stats":
            self._reply(200, ms.stats.snapshot())
        elif self.path == "/metrics":
            from .. import profiler
            # refresh this endpoint's serving counters so a scrape always
            # sees current values regardless of batch cadence
            ms.stats.publish()
            self._reply_text(
                200,
                profiler.render_prometheus() + ms.stats.render_prometheus(),
                content_type="text/plain; version=0.0.4; charset=utf-8")
        else:
            self._reply(404, {"error": "not found", "retryable": False})

    def do_POST(self):
        if self.path != "/predict":
            self._reply(404, {"error": "not found", "retryable": False})
            return
        ms = self._ms
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            raw = req["inputs"]
            inputs = {k: _np.asarray(v, dtype=_np.float32)
                      for k, v in raw.items()}
            deadline_ms = req.get("deadline_ms", ms.default_deadline_ms)
        except (KeyError, ValueError, TypeError) as e:
            self._reply(400, {"error": f"malformed request: {e}",
                              "retryable": False})
            return
        try:
            fut = ms.batcher.submit(inputs, deadline_ms=deadline_ms)
            timeout = (deadline_ms / 1e3 + 1.0) if deadline_ms else None
            outs = fut.result(timeout=timeout)
        except Overloaded as e:
            self._reply(e.status, {"error": str(e), "retryable": True},
                        retry_after="0.05")
            return
        except (DeadlineExceeded, _FutTimeout) as e:
            self._reply(504, {"error": str(e) or "deadline exceeded",
                              "retryable": True})
            return
        except Exception as e:  # noqa: BLE001 — predict failure -> 500
            self._reply(500, {"error": str(e), "retryable": False})
            return
        self._reply(200, {"outputs": [o.tolist() for o in outs]})


class _HTTPServer(ThreadingHTTPServer):
    # accept backlog must exceed the admission queue: shedding is the
    # batcher's job (fast 503), not the kernel's (silent RST under bursts)
    request_queue_size = 256
    daemon_threads = True


class ModelServer:
    """Serve a Predictor over HTTP with dynamic batching + admission
    control. `port=0` binds an ephemeral port (returned by start())."""

    def __init__(self, predictor, host="127.0.0.1", port=0,
                 max_latency_ms=5.0, max_queue=128,
                 default_deadline_ms=1000.0, stats=None, name="serve"):
        self.predictor = predictor
        buckets = (predictor.ladder.sizes if predictor.ladder is not None
                   else (1, 2, 4, 8, 16, 32))
        self.stats = stats if stats is not None else ServingStats(name)
        self.batcher = DynamicBatcher(
            predictor.predict, buckets=buckets,
            max_latency_ms=max_latency_ms, max_queue=max_queue,
            default_deadline_ms=default_deadline_ms, stats=self.stats)
        self.default_deadline_ms = default_deadline_ms
        self._host, self._port = host, port
        self._httpd = None
        self._thread = None

    @property
    def address(self):
        if self._httpd is None:
            raise MXNetError("server not started")
        return self._httpd.server_address[:2]

    def start(self):
        if self._httpd is not None:
            return self.address
        self.batcher.start()
        self._httpd = _HTTPServer((self._host, self._port), _Handler)
        self._httpd.model_server = self
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="mxtpu-serve-http",
                                        daemon=True)
        self._thread.start()
        return self.address

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.batcher.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
