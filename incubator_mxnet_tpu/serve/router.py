"""Health-checked request router over the serving fleet.

Front end of the serving control plane (control_plane.py): per-model
load balancing across READY replicas (registered, every bucket AOT-warm,
live within the fleetobs window, not draining) with the defensive-client
triad production serving systems converge on:

* **bounded jittered retries** — only on RETRYABLE failures: connect
  errors and 503/504 sheds, which the serving protocol explicitly marks
  ``retryable: true``. Application errors (400/500) are surfaced to the
  caller untouched; retrying them would re-run a request the replica
  already answered. Backoff doubles per attempt with uniform [0.5, 1.5)
  jitter and is always clipped to the request deadline.
* **hedged requests** — when the first attempt has not answered after a
  p99-derived delay (MXNET_ROUTER_HEDGE_DELAY_MS to pin it), a second
  replica is tried and the first success wins; the tail of a slow or
  dying replica costs one duplicate request, not a deadline.
* **per-replica circuit breakers** — consecutive connect/timeout
  failures open the breaker (traffic skips the replica), a half-open
  probe is admitted after the cooldown, and its outcome closes or
  re-opens. 503 sheds do NOT count: a shedding replica is alive and the
  fix is elsewhere-routing, not exile. Every transition leaves a
  flight-recorder breadcrumb and bumps an ``mxnet_router_*`` family.

Discovery is registry-polling (serve_view over the MAC'd wire every
MXNET_ROUTER_REFRESH_MS); a coordinator outage freezes the last-known
table instead of emptying it — stale routing degrades, no routing
fails. Static replica lists (``replicas=[...]``) skip discovery for
tests and single-host use.

Lock discipline: ``self._rlock`` guards the replica table + breakers
and is OUTERMOST; RouterStats' ``self._lock`` is a LEAF — stats calls
and breadcrumbs happen after _rlock is released.

Request tracing (MXNET_REQTRACE, serve/reqtrace.py): ``generate`` and
``request`` mint the trace context, every retry/hedge attempt books a
``route_attempt#n`` child span with a ``cause`` arg, the context rides
outbound calls in the ``X-MXNET-Trace`` header, breaker breadcrumbs
carry the active trace id, and the ``/generate`` done row's TTFT budget
breakdown is folded back into the request's ring record.
"""
from __future__ import annotations

import json
import logging
import queue
import random
import threading
import time
import urllib.error
import urllib.request

import numpy as _np

from .. import fault as _fault
from ..base import MXNetError
from ..util import getenv_bool, getenv_int
from . import reqtrace as _rt
from .batcher import DeadlineExceeded, Overloaded
from .stats import LatencyHistogram, reqtrace_exemplar_lines
from .. import mxsan as _mxsan

__all__ = ["Router", "RouterStats", "RouteError", "NoReplicaAvailable"]

_log = logging.getLogger("incubator_mxnet_tpu.serve.router")


class RouteError(MXNetError):
    """A replica ANSWERED with a non-retryable application error
    (400 malformed / 500 predict raised); never retried."""
    retryable = False

    def __init__(self, msg, status=500):
        super().__init__(msg)
        self.status = status


class NoReplicaAvailable(MXNetError):
    """No ready replica (none registered, none warm, or every breaker
    open); retryable — the fleet may be mid-rollout or mid-recovery."""
    retryable = True
    status = 503


def _cause_of(kind, value):
    """Map an attempt outcome to the reqtrace span `cause` vocabulary:
    ok / fatal / 503-shed / connect-error."""
    if kind == "ok":
        return "ok"
    if kind == "fatal":
        return "fatal"
    return "503-shed" if isinstance(value, Overloaded) else "connect-error"


class RouterStats:
    """``mxnet_router_*`` metric registry: flat counters + gauges + one
    request-latency histogram, same shed-nothing lock discipline as
    ServingStats (one leaf lock, O(1) hot-path updates)."""

    def __init__(self, name="router"):
        self.name = name
        self._lock = _mxsan.lock("serve/router.py", "self._lock")
        self._counters = {}
        self._gauges = {}
        self.latency = LatencyHistogram()   # internally locked

    def incr(self, field, n=1):
        with self._lock:
            self._counters[field] = self._counters.get(field, 0) + n

    def set_gauge(self, field, value):
        with self._lock:
            self._gauges[field] = value

    def snapshot(self):
        with self._lock:
            snap = {"counters": dict(self._counters),
                    "gauges": dict(self._gauges)}
        snap["latency_ms"] = {
            "p50": self.latency.percentile(50) * 1e3,
            "p99": self.latency.percentile(99) * 1e3,
            "count": self.latency.count}
        return snap

    def render_prometheus(self):
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        lines = []
        for field, val in sorted(counters.items()):
            fam = f"mxnet_router_{field}"
            lines += [f"# HELP {fam} router counter",
                      f"# TYPE {fam} counter",
                      f'{fam}{{router="{self.name}"}} {val}']
        for field, val in sorted(gauges.items()):
            fam = f"mxnet_router_{field}"
            lines += [f"# HELP {fam} router gauge",
                      f"# TYPE {fam} gauge",
                      f'{fam}{{router="{self.name}"}} {val}']
        h = self.latency.snapshot_state()
        fam = "mxnet_router_request_latency_ms"
        lines += [f"# HELP {fam} end-to-end routed request latency "
                  "(retries and hedges included)",
                  f"# TYPE {fam} histogram"]
        cum = 0
        for bound, cnt in zip(h["bounds"], h["counts"]):
            cum += cnt
            lines.append(f'{fam}_bucket{{router="{self.name}",'
                         f'le="{bound * 1e3:.6g}"}} {cum}')
        lines += [f'{fam}_bucket{{router="{self.name}",le="+Inf"}} '
                  f'{h["count"]}',
                  f'{fam}_sum{{router="{self.name}"}} {h["sum"] * 1e3:.6g}',
                  f'{fam}_count{{router="{self.name}"}} {h["count"]}']
        lines += reqtrace_exemplar_lines(
            self.latency, f'router="{self.name}"', "request_latency")
        return "\n".join(lines) + "\n"


class _Breaker:
    """Per-replica circuit breaker state; mutated ONLY under the
    router's _rlock. Methods return the transition name ("open",
    "half_open", "close") for the caller to record outside the lock."""

    __slots__ = ("state", "failures", "opened_at")

    def __init__(self):
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0

    def allow(self, now, cooldown_s):
        """(allowed, transition): closed always allows; open admits one
        half-open probe slot per cooldown. A half_open slot that was
        never exercised (the request was answered by another replica
        before the probe fired) regenerates after another cooldown —
        otherwise an unlucky rotation wedges the breaker half-open
        forever with the replica unreachable by anyone."""
        if self.state == "closed":
            return True, None
        if now - self.opened_at < cooldown_s:
            return False, None
        if self.state == "open":
            self.state = "half_open"
            self.opened_at = now
            return True, "half_open"
        self.opened_at = now        # regenerate the unexercised slot
        return True, None

    def note(self, ok, now, threshold):
        if ok:
            was = self.state
            self.state = "closed"
            self.failures = 0
            return "close" if was != "closed" else None
        self.failures += 1
        if self.state == "half_open" or (self.state == "closed"
                                         and self.failures >= threshold):
            self.state = "open"
            self.opened_at = now
            return "open"
        if self.state == "open":
            self.opened_at = now    # still failing: restart the cooldown
        return None


class Router:
    """Load-balancing front end over ready serving replicas.

    coordinator: "addr token" of the kvstore coordinator (discovery via
        serve_view), or None with a static ``replicas`` list of
        "host:port" strings (tests / single host).
    Knobs default from util.ENV_VARS (MXNET_ROUTER_*); constructor
    arguments override per instance.
    """

    def __init__(self, coordinator=None, model="default", replicas=None,
                 deadline_ms=None, retries=None, backoff_ms=None,
                 hedge_delay_ms=None, breaker_failures=None,
                 breaker_cooldown_ms=None, refresh_ms=None, stats=None,
                 name="router", slo_split=None, ttft_slo_ms=None,
                 token_slo_ms=None):
        if coordinator is None and not replicas:
            raise MXNetError("Router needs a coordinator or a static "
                             "replica list")
        self._coordinator = coordinator
        self._model = model
        self._deadline_ms = (deadline_ms if deadline_ms is not None
                             else getenv_int("MXNET_ROUTER_DEADLINE_MS"))
        self._retries = max(0, retries if retries is not None
                            else getenv_int("MXNET_ROUTER_RETRIES"))
        self._backoff_ms = max(1, backoff_ms if backoff_ms is not None
                               else getenv_int(
                                   "MXNET_ROUTER_RETRY_BACKOFF_MS"))
        self._hedge_delay_ms = (hedge_delay_ms if hedge_delay_ms is not None
                                else getenv_int(
                                    "MXNET_ROUTER_HEDGE_DELAY_MS"))
        self._breaker_failures = max(
            1, breaker_failures if breaker_failures is not None
            else getenv_int("MXNET_ROUTER_BREAKER_FAILURES"))
        self._breaker_cooldown = (
            breaker_cooldown_ms if breaker_cooldown_ms is not None
            else getenv_int("MXNET_ROUTER_BREAKER_COOLDOWN_MS")) / 1e3
        self._refresh_s = max(0.05, (
            refresh_ms if refresh_ms is not None
            else getenv_int("MXNET_ROUTER_REFRESH_MS")) / 1e3)
        # SLO-split placement (MXNET_ROUTER_SLO_SPLIT): rank candidates
        # by observed tail-latency headroom against per-role SLOs
        # instead of pure rotation — see _candidates
        self._slo_split = (slo_split if slo_split is not None
                           else getenv_bool("MXNET_ROUTER_SLO_SPLIT"))
        self._ttft_slo_ms = float(
            ttft_slo_ms if ttft_slo_ms is not None
            else getenv_int("MXNET_ROUTER_TTFT_SLO_MS"))
        self._token_slo_ms = float(
            token_slo_ms if token_slo_ms is not None
            else getenv_int("MXNET_ROUTER_TOKEN_SLO_MS"))
        self.stats = stats if stats is not None else RouterStats(name)
        self._rng = random.Random()
        self._rlock = _mxsan.lock(
            "serve/router.py", "self._rlock")  # replica table + breakers;
        #                                 OUTERMOST, stats lock is a leaf
        self._replicas = {}             # rid -> {"addr", "ready", "generation"}
        self._breakers = {}             # rid -> _Breaker
        self._rr = {}                   # per-role round-robin cursors
        self._client = None
        self._req_seq = 0               # ship-key uniquifier (under _rlock)
        self._stop = threading.Event()
        self._thread = None
        self._metrics_httpd = None
        if replicas:
            with self._rlock:
                for i, addr in enumerate(replicas):
                    rid = f"static{i}"
                    self._replicas[rid] = {"addr": str(addr), "ready": True,
                                           "generation": -1, "role": "both",
                                           "load": {}}
                    self._breakers[rid] = _Breaker()

    # -- discovery ------------------------------------------------------
    def start(self):
        if self._coordinator is not None and self._thread is None:
            self.refresh()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._discovery_loop, name="mxtpu-router-discovery",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._metrics_httpd is not None:
            try:
                self._metrics_httpd.shutdown()
                self._metrics_httpd.server_close()
            except OSError:
                pass
            self._metrics_httpd = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _discovery_loop(self):
        while not self._stop.wait(self._refresh_s):
            try:
                self.refresh()
            except (MXNetError, OSError, ConnectionError):
                # coordinator unreachable: keep the last-known table
                # (stale routing degrades; empty routing fails) and
                # redial next tick
                if self._client is not None:
                    self._client.close()
                    self._client = None

    def refresh(self):
        """One discovery poll: pull serve_view, rebuild the table.
        Breakers persist across refreshes for surviving replica ids."""
        if self._client is None:
            from .. import kvstore_server as _ksrv
            self._client = _ksrv.connect_async_server(self._coordinator)
        view = self._client.call("serve_view", self._model)
        rows = view["replicas"]
        ready = 0
        with self._rlock:
            table = {}
            for rid, row in rows.items():
                eligible = (row["ready"] and row["live"]
                            and not row.get("draining"))
                ready += 1 if eligible else 0
                table[rid] = {"addr": row["http_addr"], "ready": eligible,
                              "generation": row["generation"],
                              "role": row.get("role", "both"),
                              "load": dict(row.get("load") or {})}
                if rid not in self._breakers:
                    self._breakers[rid] = _Breaker()
            self._replicas = table
            for rid in [r for r in self._breakers if r not in table]:
                del self._breakers[rid]
        self.stats.set_gauge("replicas_known", len(rows))
        self.stats.set_gauge("replicas_ready", ready)
        return view

    def set_replicas(self, replicas):
        """Replace the static table (tests / manual operation)."""
        with self._rlock:
            self._replicas = {
                f"static{i}": {"addr": str(a), "ready": True,
                               "generation": -1, "role": "both",
                               "load": {}}
                for i, a in enumerate(replicas)}
            self._breakers = {rid: self._breakers.get(rid, _Breaker())
                              for rid in self._replicas}

    # -- breaker plumbing ----------------------------------------------
    def _candidates(self, role=None):
        """Ready, breaker-admitted (rid, addr) pairs; breaker half-open
        transitions are recorded on the way.

        Role-aware policy (disaggregated serving):
          role=None     all ready replicas, round-robin — the classic
                        /predict path.
          role="prefill" replicas whose role is prefill or both,
                        DEDICATED prefill replicas first (they exist to
                        absorb the compute burst; a colocated "both"
                        replica is the fallback), round-robin per tier —
                        the TTFT SLO is served by never queueing a
                        prompt behind decode steps.
          role="decode" replicas whose role is decode or both, ordered
                        by KV page headroom (kv_pages_free from the v2
                        beat's load report, descending) — inter-token
                        SLOs die when a stream lands on a replica about
                        to shed on pages. Unreported headroom sorts
                        last; ties break round-robin.

        SLO-split refinement (MXNET_ROUTER_SLO_SPLIT): replicas report
        observed tail latencies in their load beat (prefill_p99_ms /
        ttft_p99_ms / token_p99_ms, serve/server.py load_report) and
        candidates are ranked by SLO HEADROOM — prefill by
        MXNET_ROUTER_TTFT_SLO_MS minus the replica's prefill/ttft p99
        (dedicated tier still first), decode by MXNET_ROUTER_TOKEN_SLO_MS
        minus inter-token p99, kv_pages_free as the tiebreak. A replica
        with no latency evidence yet scores headroom 0: below anything
        proven inside its SLO, above anything proven outside it —
        "no evidence" is not "fast". Sorts are stable, so equal
        headroom preserves the round-robin rotation.
        """
        now = time.monotonic()
        transitions = []
        with self._rlock:
            out = []
            for rid in sorted(self._replicas):
                info = self._replicas[rid]
                if not info["ready"]:
                    continue
                rrole = info.get("role", "both")
                if role is not None and rrole not in (role, "both"):
                    continue
                allowed, moved = self._breakers[rid].allow(
                    now, self._breaker_cooldown)
                if moved:
                    transitions.append((rid, moved))
                if allowed:
                    out.append((rid, info["addr"], rrole,
                                info.get("load") or {}))
            self._rr[role] = self._rr.get(role, 0) + 1
            k = self._rr[role] % len(out) if out else 0
        for rid, moved in transitions:
            self._record_transition(rid, moved)
        out = out[k:] + out[:k]         # round-robin rotation
        if role == "prefill":
            if self._slo_split:
                out.sort(key=lambda c: (c[2] != "prefill",
                                        -self._ttft_headroom(c[3])))
            else:
                out.sort(key=lambda c: c[2] != "prefill")  # dedicated first
        elif role == "decode":
            if self._slo_split:
                out.sort(key=lambda c: (-self._token_headroom(c[3]),
                                        -c[3].get("kv_pages_free", -1)))
            else:
                out.sort(key=lambda c: -c[3].get("kv_pages_free", -1))
        return [(rid, addr) for rid, addr, _, _ in out]

    def _ttft_headroom(self, load):
        """TTFT-SLO headroom in ms from a replica's load beat. Dedicated
        prefill replicas report prefill_p99_ms; colocated "both"
        replicas report the decode scheduler's ttft_p99_ms. No evidence
        scores 0 (neutral), so never-measured replicas neither jump the
        queue nor get starved."""
        p99 = load.get("prefill_p99_ms", load.get("ttft_p99_ms"))
        if p99 is None:
            return 0.0
        return self._ttft_slo_ms - float(p99)

    def _token_headroom(self, load):
        """Inter-token-SLO headroom in ms (token_p99_ms from the decode
        scheduler's per-token gap histogram); no evidence scores 0."""
        p99 = load.get("token_p99_ms")
        if p99 is None:
            return 0.0
        return self._token_slo_ms - float(p99)

    def _note_result(self, rid, ok):
        """Feed a call outcome to the replica's breaker (connect-layer
        truth only: 503 sheds never reach here as failures)."""
        now = time.monotonic()
        with self._rlock:
            br = self._breakers.get(rid)
            moved = br.note(ok, now, self._breaker_failures) if br else None
        if moved:
            self._record_transition(rid, moved)

    def _record_transition(self, rid, transition):
        self.stats.incr(f"breaker_{transition}_total")
        # the active request trace id (if any) rides the breadcrumb so a
        # kill -9 postmortem joins the request trace by trace_id
        _fault.flight_record("router_breaker", router=self.stats.name,
                             replica=rid, transition=transition,
                             trace=_rt.current_trace_id())
        _log.warning("router[%s] breaker %s -> %s",
                     self.stats.name, rid, transition)

    def breaker_states(self):
        with self._rlock:
            return {rid: br.state for rid, br in self._breakers.items()}

    def replica_table(self):
        with self._rlock:
            return {rid: dict(info) for rid, info in self._replicas.items()}

    # -- request path ---------------------------------------------------
    def _backoff_s(self, attempt, deadline, retry_after=None):
        """Jittered exponential backoff, clipped to the deadline. When
        the shedding replica sent a Retry-After header it KNOWS when it
        will have capacity — honor it as a floor instead of hammering
        back early with a shorter jittered guess."""
        base = min(1.0, self._backoff_ms / 1e3 * (2 ** (attempt - 1)))
        jittered = base * self._rng.uniform(0.5, 1.5)
        if retry_after is not None:
            jittered = max(jittered, float(retry_after))
        return max(0.0, min(jittered, deadline - time.monotonic() - 1e-3))

    @staticmethod
    def _parse_retry_after(headers):
        """Seconds from a 503's Retry-After header (delta-seconds form
        only — the serving protocol emits "0.05"-style floats), or None
        when absent/unparseable."""
        raw = headers.get("Retry-After") if headers is not None else None
        if raw is None:
            return None
        try:
            val = float(raw)
        except (TypeError, ValueError):
            return None
        return val if val >= 0 else None

    def _hedge_delay_s(self):
        if self._hedge_delay_ms > 0:
            return self._hedge_delay_ms / 1e3
        # p99-derived: needs a populated histogram; a 50ms floor covers
        # the cold start and stops hedging on micro-jitter
        if self.stats.latency.count >= 20:
            return max(0.01, self.stats.latency.percentile(99))
        return 0.05

    def request(self, inputs, deadline_ms=None):
        """Route one prediction: dict of UNBATCHED sample arrays ->
        list of per-output numpy arrays. Raises RouteError (replica
        application error, non-retryable), NoReplicaAvailable, or
        DeadlineExceeded once the deadline/retry budget is spent."""
        if deadline_ms is None:
            deadline_ms = self._deadline_ms
        deadline = time.monotonic() + deadline_ms / 1e3
        inputs_json = {k: _np.asarray(v).tolist() for k, v in inputs.items()}
        self.stats.incr("requests_total")
        ctx = _rt.mint(deadline_ms=deadline_ms)
        t0 = time.monotonic()
        last_err = None
        with _rt.activate(ctx):
            for attempt in range(self._retries + 1):
                qt0 = time.perf_counter()
                if attempt:
                    self.stats.incr("retries_total")
                    pause = self._backoff_s(
                        attempt, deadline,
                        retry_after=getattr(last_err, "retry_after_s", None))
                    if pause > 0:
                        time.sleep(pause)
                if time.monotonic() >= deadline:
                    break
                cands = self._candidates()
                if not cands:
                    self.stats.incr("no_replica_total")
                    last_err = NoReplicaAvailable(
                        f"no ready replica for model {self._model!r}")
                    continue
                if ctx is not None:
                    _rt.observe(ctx, "router_queue",
                                (time.perf_counter() - qt0) * 1e3, t0=qt0)
                kind, value = self._attempt(cands, inputs_json, deadline,
                                            attempt_no=attempt)
                if kind == "ok":
                    dt = time.monotonic() - t0
                    self.stats.latency.observe(
                        dt, trace=ctx.trace_id
                        if ctx is not None and ctx.sampled else None)
                    self.stats.incr("responses_ok_total")
                    if ctx is not None:
                        _rt.finish(ctx, status="ok", total_ms=dt * 1e3)
                    return value
                if ctx is not None:
                    _rt.promote(ctx, cause=_cause_of(kind, value),
                                detail=value)
                if kind == "fatal":
                    self.stats.incr("responses_fatal_total")
                    _rt.finish(ctx, status="error", cause="fatal")
                    raise value
                last_err = value
        self.stats.incr("requests_failed_total")
        _rt.finish(ctx, status="error", cause="retries-exhausted")
        if isinstance(last_err, MXNetError):
            raise last_err
        raise DeadlineExceeded(
            f"router deadline {deadline_ms}ms exhausted "
            f"({self._retries} retries)")

    def _attempt(self, cands, inputs_json, deadline, attempt_no=0):
        """One (possibly hedged) attempt against up to two replicas.
        Returns ("ok", outputs) | ("retryable", err) | ("fatal", err)."""
        results = queue.Queue()
        ctx = _rt.current()

        def run(rid, addr, hedged):
            # worker threads don't inherit thread-locals: re-activate the
            # request context so the outbound call carries the header and
            # the per-attempt span books against the right trace
            with _rt.activate(ctx):
                at0 = time.perf_counter()
                out = self._one_call(rid, addr, inputs_json, deadline)
                if ctx is not None:
                    akind, avalue = out
                    cause = ("hedge-win" if hedged and akind == "ok"
                             else _cause_of(akind, avalue))
                    _rt.attempt(ctx, attempt_no, cause,
                                (time.perf_counter() - at0) * 1e3, t0=at0,
                                hedged=hedged, replica=rid)
                results.put((out, rid, hedged))

        threading.Thread(target=run, args=(*cands[0], False),
                         name="mxtpu-router-attempt", daemon=True).start()
        outstanding, hedge_fired = 1, False
        first_failure = None
        while outstanding:
            now = time.monotonic()
            if now >= deadline:
                return ("retryable",
                        first_failure or DeadlineExceeded(
                            "deadline during routed attempt"))
            if not hedge_fired and len(cands) > 1:
                wait = min(self._hedge_delay_s(), deadline - now)
            else:
                wait = deadline - now
            try:
                (kind, value), rid, hedged = results.get(
                    timeout=max(1e-3, wait))
            except queue.Empty:
                if not hedge_fired and len(cands) > 1:
                    hedge_fired = True
                    outstanding += 1
                    self.stats.incr("hedges_total")
                    if ctx is not None:
                        _rt.observe(ctx, "hedge", wait * 1e3,
                                    args={"replica": cands[1][0]})
                    threading.Thread(target=run, args=(*cands[1], True),
                                     name="mxtpu-router-hedge",
                                     daemon=True).start()
                continue
            outstanding -= 1
            if kind == "ok":
                if hedged:
                    self.stats.incr("hedge_wins_total")
                return ("ok", value)
            if kind == "fatal":
                return ("fatal", value)
            if first_failure is None:
                first_failure = value
            # retryable: if a hedge is still in flight, wait it out
        return ("retryable", first_failure)

    def generate(self, prompt, max_new_tokens=None, deadline_ms=None):
        """Route one decode stream (/generate) and return its full token
        list. Retry is WHOLE-STREAM: a stream cut mid-flight (replica
        killed, connection reset) restarts from the prompt on the next
        candidate — greedy decode is deterministic, so the retried
        stream reproduces the tokens the dead replica already sent.
        Connect-layer failures feed the replica's breaker exactly like
        /predict; 503 sheds retry without breaker blame. No hedging: a
        duplicate stream doubles token work for tail latency decode
        rarely has.

        When the fleet has a DEDICATED prefill replica (role
        "prefill"), the stream is split: /prefill on the prefill tier
        ships the prompt's KV pages to the coordinator's page store
        under a fresh ship_key, then /generate on a decode-tier replica
        imports them — the decode replica never recomputes the prompt.
        A dead prefill replica blames ITS breaker and the whole stream
        restarts (greedy decode is deterministic, so the client still
        sees exactly one coherent token sequence); a decode-side shed
        retries the decode leg with the same ship_key (the fetch is
        non-destructive)."""
        if deadline_ms is None:
            deadline_ms = self._deadline_ms
        deadline = time.monotonic() + deadline_ms / 1e3
        self.stats.incr("requests_total")
        ctx = _rt.mint(deadline_ms=deadline_ms)
        t0 = time.monotonic()
        last_err = None
        with _rt.activate(ctx):
            for attempt in range(self._retries + 1):
                qt0 = time.perf_counter()
                if attempt:
                    self.stats.incr("retries_total")
                    pause = self._backoff_s(
                        attempt, deadline,
                        retry_after=getattr(last_err, "retry_after_s", None))
                    if pause > 0:
                        time.sleep(pause)
                if time.monotonic() >= deadline:
                    break
                if ctx is not None:
                    _rt.observe(ctx, "router_queue",
                                (time.perf_counter() - qt0) * 1e3, t0=qt0)
                at0 = time.perf_counter()
                if self._has_dedicated_prefill():
                    kind, value = self._split_stream(prompt, max_new_tokens,
                                                     deadline)
                else:
                    cands = self._candidates(role="decode")
                    if not cands:
                        self.stats.incr("no_replica_total")
                        last_err = NoReplicaAvailable(
                            f"no ready replica for model {self._model!r}")
                        continue
                    kind, value = self._one_stream(
                        cands[0][0], cands[0][1], prompt, max_new_tokens,
                        deadline)
                if ctx is not None:
                    _rt.attempt(ctx, attempt, _cause_of(kind, value),
                                (time.perf_counter() - at0) * 1e3, t0=at0)
                if kind == "ok":
                    dt = time.monotonic() - t0
                    self.stats.latency.observe(
                        dt, trace=ctx.trace_id
                        if ctx is not None and ctx.sampled else None)
                    self.stats.incr("responses_ok_total")
                    if ctx is not None:
                        _rt.finish(ctx, status="ok", ttft_ms=ctx.ttft_ms,
                                   total_ms=dt * 1e3, budget=ctx.budget,
                                   slo_ms=self._ttft_slo_ms)
                    return value
                if ctx is not None:
                    _rt.promote(ctx, cause=_cause_of(kind, value),
                                detail=value)
                if kind == "fatal":
                    self.stats.incr("responses_fatal_total")
                    _rt.finish(ctx, status="error", cause="fatal")
                    raise value
                last_err = value
        self.stats.incr("requests_failed_total")
        _rt.finish(ctx, status="error", cause="retries-exhausted")
        if isinstance(last_err, MXNetError):
            raise last_err
        raise DeadlineExceeded(
            f"router deadline {deadline_ms}ms exhausted "
            f"({self._retries} retries)")

    def _has_dedicated_prefill(self):
        """True when the split prefill->decode path applies: a ready
        DEDICATED prefill replica exists, a decode-capable replica
        exists, and a coordinator page store is reachable to ship
        through. All-"both" fleets take the classic colocated path."""
        if self._coordinator is None:
            return False
        with self._rlock:
            roles = [info.get("role", "both")
                     for info in self._replicas.values() if info["ready"]]
        return ("prefill" in roles
                and any(r in ("decode", "both") for r in roles))

    def _split_stream(self, prompt, max_new_tokens, deadline):
        """One disaggregated attempt: /prefill on the prefill tier
        (ships KV pages under a fresh ship_key), then /generate with
        that ship_key on the decode tier. Returns ("ok", tokens) |
        ("retryable", err) | ("fatal", err); prefill-leg failures blame
        the PREFILL replica's breaker, decode-leg failures the decode
        replica's — chaos on one tier never exiles the other."""
        with self._rlock:
            droles = {rid: info.get("role", "both")
                      for rid, info in self._replicas.items()}
        pcands = [(rid, addr) for rid, addr in self._candidates("prefill")
                  if droles.get(rid) == "prefill"]
        dcands = self._candidates(role="decode")
        if not dcands:
            self.stats.incr("no_replica_total")
            return ("retryable", NoReplicaAvailable(
                f"no ready decode replica for model {self._model!r}"))
        if not pcands:
            # the prefill tier is gone (every breaker open, or the last
            # prefill replica died and the live window has not expired
            # yet): degrade to colocated local prefill on the decode
            # tier instead of failing the request — same graceful-
            # degradation contract the breakers give /predict
            self.stats.incr("disagg_fallbacks_total")
            return self._one_stream(dcands[0][0], dcands[0][1], prompt,
                                    max_new_tokens, deadline)
        from .disagg import ship_key_for
        with self._rlock:
            self._req_seq += 1
            seq = self._req_seq
        ship_key = ship_key_for(
            self._model, f"{seq}-{self._rng.getrandbits(32):08x}")
        kind, value = self._prefill_call(pcands[0][0], pcands[0][1],
                                         prompt, ship_key, deadline)
        if kind != "ok":
            return (kind, value)
        self.stats.incr("prefill_routed_total")
        kind, value = self._one_stream(dcands[0][0], dcands[0][1], prompt,
                                       max_new_tokens, deadline,
                                       ship_key=ship_key)
        if kind == "ok":
            self.stats.incr("disagg_streams_total")
        return (kind, value)

    def _prefill_call(self, rid, addr, prompt, ship_key, deadline):
        """One HTTP /prefill against a prefill-tier replica; same
        outcome classification as /predict (connect errors feed THIS
        replica's breaker, 503 sheds retry without blame)."""
        timeout = max(1e-3, deadline - time.monotonic())
        body = json.dumps({"prompt": [int(t) for t in prompt],
                           "ship": True,
                           "ship_key": ship_key}).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        ctx = _rt.current()
        if ctx is not None:
            headers[_rt.TRACE_HEADER] = _rt.to_header(ctx)
        try:
            _fault.inject("route")      # MXNET_FAULT_INJECT: route@n
            req = urllib.request.Request(
                f"http://{addr}/prefill", data=body,
                headers=headers, method="POST")
            with urllib.request.urlopen(req, timeout=timeout) as r:
                payload = json.loads(r.read().decode("utf-8"))
            self._note_result(rid, True)
            if ctx is not None:
                # the replica's measured prefill/ship legs become baggage
                # on the /generate header so the decode side can complete
                # the TTFT budget breakdown
                for leg in ("prefill_ms", "ship_ms"):
                    if payload.get(leg) is not None:
                        ctx.baggage[leg] = float(payload[leg])
            return ("ok", payload)
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode("utf-8"))
            except (ValueError, OSError):
                detail = {"error": str(e)}
            self._note_result(rid, True)
            if e.code in (503, 504) and detail.get("retryable", True):
                self.stats.incr("sheds_total")
                err = Overloaded(
                    f"prefill replica {rid} shed ({e.code}): "
                    f"{detail.get('error', '')}")
                err.retry_after_s = self._parse_retry_after(e.headers)
                return ("retryable", err)
            return ("fatal", RouteError(
                f"prefill replica {rid}: {detail.get('error', e)}",
                status=e.code))
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            self.stats.incr("connect_errors_total")
            self._note_result(rid, False)
            return ("retryable", NoReplicaAvailable(
                f"prefill replica {rid} at {addr} unreachable: {e}"))

    def _one_stream(self, rid, addr, prompt, max_new_tokens, deadline,
                    ship_key=None):
        """One streamed /generate against one replica, consuming the
        ndjson chunks until the {"done"} line. A stream that dies before
        "done" — reset, timeout, truncation — counts as a connect-layer
        breaker failure: the replica proved unable to FINISH, which for
        streams is the health contract.

        Token accounting is PER-ATTEMPT: ``tokens`` below is a fresh
        local tally, folded into RouterStats exactly once when this
        attempt settles — stream_tokens_total on "ok", stream_tokens_
        discarded_total for partial tokens a failed attempt received
        before the cut/shed. A whole-stream retry replays the prompt
        and re-sends those tokens, so folding as-they-arrive would
        double-count every replayed token; folding only the winning
        attempt keeps stream_tokens_total equal to what callers were
        actually handed."""
        import http.client
        timeout = max(1e-3, deadline - time.monotonic())
        req_body = {"prompt": [int(t) for t in prompt],
                    "max_new_tokens": max_new_tokens,
                    "stream": True,
                    "deadline_ms": timeout * 1e3}
        if ship_key is not None:
            req_body["ship_key"] = ship_key
        body = json.dumps(req_body).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        ctx = _rt.current()
        if ctx is not None:
            # router_ms = everything this router spent so far that is NOT
            # the prefill/ship legs already attributed by the prefill
            # replica (candidate selection, backoff, failed attempts)
            elapsed = (time.perf_counter() - ctx.t0) * 1e3
            legs = sum(ctx.baggage.get(k, 0.0)
                       for k in ("prefill_ms", "ship_ms"))
            headers[_rt.TRACE_HEADER] = _rt.to_header(
                ctx, router_ms=max(0.0, elapsed - legs))
        tokens = []
        try:
            _fault.inject("route")      # MXNET_FAULT_INJECT: route@n
            req = urllib.request.Request(
                f"http://{addr}/generate", data=body,
                headers=headers, method="POST")
            with urllib.request.urlopen(req, timeout=timeout) as r:
                for line in r:
                    if not line.strip():
                        continue
                    row = json.loads(line.decode("utf-8"))
                    if "token" in row:
                        if ctx is not None and not tokens:
                            ctx.mark_first_token()
                        tokens.append(int(row["token"]))
                    elif row.get("done"):
                        self._note_result(rid, True)
                        self.stats.incr("stream_tokens_total", len(tokens))
                        if ctx is not None and "budget" in row:
                            ctx.budget = row["budget"]
                        return ("ok", tokens)
                    elif "error" in row:
                        # in-band error line: the replica answered
                        # decisively — not a breaker failure. Tokens
                        # streamed before it are dead: a retry replays
                        # them, so they must NOT hit stream_tokens_total
                        self._note_result(rid, True)
                        self._discard_tokens(tokens)
                        if row.get("retryable"):
                            self.stats.incr("sheds_total")
                            return ("retryable", Overloaded(
                                f"replica {rid} shed mid-stream: "
                                f"{row['error']}"))
                        return ("fatal", RouteError(
                            f"replica {rid}: {row['error']}"))
            self._note_result(rid, False)
            self._discard_tokens(tokens)
            return ("retryable", NoReplicaAvailable(
                f"replica {rid} stream ended without done marker "
                f"({len(tokens)} tokens in)"))
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode("utf-8"))
            except (ValueError, OSError):
                detail = {"error": str(e)}
            self._note_result(rid, True)
            if e.code in (503, 504) and detail.get("retryable", True):
                self.stats.incr("sheds_total")
                err = Overloaded(
                    f"replica {rid} shed ({e.code}): "
                    f"{detail.get('error', '')}")
                err.retry_after_s = self._parse_retry_after(e.headers)
                return ("retryable", err)
            return ("fatal", RouteError(
                f"replica {rid}: {detail.get('error', e)}", status=e.code))
        except (urllib.error.URLError, http.client.HTTPException,
                ConnectionError, TimeoutError, OSError, ValueError) as e:
            self.stats.incr("connect_errors_total")
            self._note_result(rid, False)
            self._discard_tokens(tokens)
            return ("retryable", NoReplicaAvailable(
                f"replica {rid} at {addr} died mid-stream after "
                f"{len(tokens)} tokens: {e}"))

    def _discard_tokens(self, tokens):
        """Fold a failed attempt's partial token tally into the discard
        counter (the retry will replay them from the prompt)."""
        if tokens:
            self.stats.incr("stream_tokens_discarded_total", len(tokens))

    def _one_call(self, rid, addr, inputs_json, deadline):
        """One HTTP /predict against one replica. Returns (kind, value);
        classification is the whole policy: connect errors feed the
        breaker and retry, 503/504 sheds retry without breaker blame,
        anything the replica answered decisively is final."""
        timeout = max(1e-3, deadline - time.monotonic())
        body = json.dumps({"inputs": inputs_json,
                           "deadline_ms": timeout * 1e3}).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        ctx = _rt.current()
        if ctx is not None:
            headers[_rt.TRACE_HEADER] = _rt.to_header(ctx)
        try:
            _fault.inject("route")      # MXNET_FAULT_INJECT: route@n
            req = urllib.request.Request(
                f"http://{addr}/predict", data=body,
                headers=headers, method="POST")
            with urllib.request.urlopen(req, timeout=timeout) as r:
                payload = json.loads(r.read().decode("utf-8"))
            self._note_result(rid, True)
            return ("ok", [_np.asarray(o) for o in payload["outputs"]])
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode("utf-8"))
            except (ValueError, OSError):
                detail = {"error": str(e)}
            # an HTTP answer proves the replica's wire: not a breaker
            # failure, whatever the status
            self._note_result(rid, True)
            if e.code in (503, 504) and detail.get("retryable", True):
                self.stats.incr("sheds_total")
                err = Overloaded(
                    f"replica {rid} shed ({e.code}): "
                    f"{detail.get('error', '')}")
                err.retry_after_s = self._parse_retry_after(e.headers)
                return ("retryable", err)
            return ("fatal", RouteError(
                f"replica {rid}: {detail.get('error', e)}", status=e.code))
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            self.stats.incr("connect_errors_total")
            self._note_result(rid, False)
            return ("retryable", NoReplicaAvailable(
                f"replica {rid} at {addr} unreachable: {e}"))

    # -- observability --------------------------------------------------
    def render_prometheus(self):
        """RouterStats families + per-replica breaker-state gauges."""
        with self._rlock:
            states = {rid: br.state for rid, br in self._breakers.items()}
        lines = [self.stats.render_prometheus().rstrip("\n"),
                 "# HELP mxnet_router_breaker_state per-replica circuit "
                 "breaker (0 closed, 1 half_open, 2 open)",
                 "# TYPE mxnet_router_breaker_state gauge"]
        code = {"closed": 0, "half_open": 1, "open": 2}
        for rid, st in sorted(states.items()):
            lines.append(
                f'mxnet_router_breaker_state{{router="{self.stats.name}",'
                f'replica="{rid}"}} {code[st]}')
        return "\n".join(lines) + "\n"

    def start_metrics_http(self, host="127.0.0.1", port=0, extra=()):
        """Serve /metrics (router families + any ``extra`` renderer
        callables, e.g. a RolloutManager's) and /replicas JSON on an
        ephemeral port; returns (host, port)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        router = self
        extras = tuple(extra)

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code, text, ctype="text/plain; charset=utf-8"):
                data = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    if self.path == "/metrics":
                        body = (router.render_prometheus()
                                + _rt.render_prometheus(
                                    f'router="{router.stats.name}"')
                                + "".join(fn() for fn in extras))
                        self._send(200, body, "text/plain; version=0.0.4; "
                                              "charset=utf-8")
                    elif self.path == "/replicas":
                        self._send(200, json.dumps(router.replica_table()),
                                   "application/json")
                    elif self.path == "/debugz/requests":
                        self._send(200, json.dumps(_rt.ring_snapshot()),
                                   "application/json")
                    else:
                        self._send(404, "not found\n")
                except Exception as e:      # noqa: BLE001
                    self._send(500, f"error: {e}\n")

            def log_message(self, fmt, *args):
                _log.debug("router http: " + fmt, *args)

        srv = ThreadingHTTPServer((host, port), _Handler)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever,
                         name="mxtpu-router-metrics", daemon=True).start()
        self._metrics_httpd = srv
        return srv.server_address[:2]
