"""Speculative decoding: draft-propose / batched-verify over the paged
KV-cache.

Plain continuous decode (serve/decode.py) pays ONE target-model
dispatch per emitted token. Speculative decoding buys tokens in bulk: a
cheap DRAFT proposes ``k`` tokens per stream per iteration, and exactly
ONE fixed-shape batched VERIFY executable scores all ``k+1`` positions
of every active slot in a single target-model step. The acceptance rule
keeps the longest prefix of the draft that agrees with the target's own
picks, then appends the target's next token (the "bonus"), so emitted
streams are IDENTICAL to plain decode — under greedy sampling,
bit-identical by construction, because every emitted token is the
target's argmax given an accepted (hence correct) context.

The verify executable extends PR 13's zero-retrace contract to a block
of ``G = k+1`` query tokens per slot:

* its shape is fixed at construction (``slots`` x ``G``) — per-stream
  speculation depth varies at runtime by PADDING rows to position -1,
  never by retracing;
* padding/idle rows write out-of-bounds (scatter ``mode="drop"``) and
  read a clamped one-key window, exactly like the decode executable's
  idle slots;
* the attention read path is ``paged_attention_multiquery`` — one
  shared page walk per sequence serves all G queries
  (parallel/paged_attention.py), so verify costs one pass over the KV
  history, not G.

Acceptance rule (greedy). For a slot whose pending token is ``t0`` at
write position ``p0`` with draft ``d1..dk``: verify row ``g`` carries
token ``[t0, d1, .., dk][g]`` at position ``p0+g`` and attends over
positions ``0..p0+g``; its argmax ``y[g]`` is therefore the target's
next token AFTER consuming that row. Accept ``d_j`` iff
``d_j == y[j-1]`` and all earlier drafts were accepted; with ``m``
accepted, emit ``d1..dm`` then ``y[m]`` — m+1 tokens, each provably the
token plain greedy decode would have emitted. KV rows written for
rejected drafts are never read: every later read window is re-covered
by that step's own scatter of verified tokens first.

Page-rollback invariant. Speculation never claims pages: admission
already claimed every page a stream can EVER touch (decode.py), spec
write positions are clamped to the stream's owned capacity
(``k_s <= owned_rows - 1 - p0``), and shared prefix-cache pages hold
only positions below the prompt length, which speculative writes never
reach (any shared tail page was CoW-forked at admission). Rejection
rolls back the draft state and the slot position — page ownership is
untouched — so cancel/drain still returns the allocator to
``live == 0`` with zero leaked pages.

The draft here is SELF-DRAFTING: a host-side numpy replica of the
target's single-layer attention math (same params, float32), so the
measured accept rate is near 1.0 and the speedup bound is the dispatch
amortization (one device step per m+1 tokens). A real deployment plugs
a smaller model in via ``draft_factory``; the acceptance rule does not
depend on draft quality for CORRECTNESS, only for speed.

Adaptive k: each stream carries an EMA of its accept fraction; below
``MXNET_SPEC_ACCEPT_FLOOR_PCT`` the per-stream depth shrinks toward 1
(a bad draft degrades to plain decode cost, never below), and at
sustained near-full acceptance it regrows toward ``MXNET_SPEC_K``.

Lock hierarchy (tools/mxlint/lock_order.py): ``self._compile_lock``
guards verify-executable construction only; draft state is touched
exclusively by the scheduler loop thread and needs no lock.
"""
from __future__ import annotations

import math
import threading

import numpy as _np

from ..base import MXNetError
from .. import util
from .. import mxsan as _mxsan

__all__ = ["DraftState", "SpecDecoder"]

# EMA weight for the per-stream accept-rate estimate: 0.5 reacts within
# a couple of iterations, which matters because a stream only lives for
# max_new_tokens of them
_EMA_ALPHA = 0.5
# accept fraction at/above which adaptive k regrows toward the cap
_GROW_AT = 0.9


class DraftState:
    """Host-side numpy draft model for one stream (self-drafting).

    Replicates the target's single-layer attention LM in float32 numpy:
    a dense per-stream K/V history (``rows`` token rows, (H, D) each)
    stands in for the paged pool, ``propose`` runs the same
    embed -> qkv -> causal attention -> argmax math the device executes.
    Draft K/V never touches the device and never touches the paged pool
    — rejection rollback is a truncate of these arrays, nothing else.

    Invariant between iterations: ``rows == p0`` where ``p0`` is the
    slot's pending write position, i.e. the history holds exactly the
    tokens whose KV the target has COMMITTED (prompt + accepted tokens),
    not the pending token itself.
    """

    def __init__(self, params, num_heads, head_dim, prompt):
        self._p = params
        self._h = int(num_heads)
        self._d = int(head_dim)
        self._scale = 1.0 / math.sqrt(self._d)
        h = params["emb"][_np.asarray(prompt, _np.int64)]      # (n, E)
        self._K = (h @ params["wk"]).reshape(-1, self._h, self._d)
        self._V = (h @ params["wv"]).reshape(-1, self._h, self._d)

    @property
    def rows(self):
        return len(self._K)

    def _append_row(self, token):
        h = self._p["emb"][int(token)]                          # (E,)
        self._K = _np.concatenate(
            [self._K, (h @ self._p["wk"]).reshape(1, self._h, self._d)])
        self._V = _np.concatenate(
            [self._V, (h @ self._p["wv"]).reshape(1, self._h, self._d)])
        return h

    def _advance(self, token):
        """Append ``token``'s KV row and return the draft's greedy next
        token — the same attend-over-0..pos window the target uses."""
        p = self._p
        h = self._append_row(token)
        q = (h @ p["wq"]).reshape(self._h, self._d) * self._scale
        s = _np.einsum("hd,thd->ht", q, self._K)                # (H, T)
        s = s - s.max(axis=-1, keepdims=True)
        w = _np.exp(s)
        w /= w.sum(axis=-1, keepdims=True)
        a = _np.einsum("ht,thd->hd", w, self._V).reshape(-1)
        o = a @ p["wo"] + h
        return int(_np.argmax(o @ p["w_out"]))

    def propose(self, last_token, k):
        """Draft ``k`` tokens continuing from the pending ``last_token``
        (appends k rows: last_token and the first k-1 drafts)."""
        out = []
        t = int(last_token)
        for _ in range(int(k)):
            t = self._advance(t)
            out.append(t)
        return out

    def sync(self, base, written):
        """Reconcile with the verify outcome: ``written`` are the tokens
        now COMMITTED at positions ``base..base+len(written)-1`` (the
        pending token plus the accepted drafts). Rows proposed beyond
        them are rolled back; rows not yet computed (full acceptance,
        zero-k steps) are appended."""
        target = int(base) + len(written)
        if self.rows > target:
            self._K = self._K[:target]
            self._V = self._V[:target]
        while self.rows < target:
            self._append_row(written[self.rows - int(base)])


class SpecDecoder:
    """The verify executable + draft factory + adaptive-k policy for one
    DecodePredictor's geometry.

    ONE fixed-shape verify executable per (slots, G, geometry) — key
    ``serve:verify[s<slots>,g<G>,<geom>]`` in the two-tier compile
    cache, AOT-warmable like the decode executable so a warm boot
    deserializes it from disk with zero compiles.
    """

    def __init__(self, predictor, *, k=None, adapt=None,
                 accept_floor_pct=None, draft_factory=None):
        self.predictor = predictor
        self.k = int(k if k is not None
                     else util.getenv_int("MXNET_SPEC_K"))
        if self.k < 1:
            raise MXNetError(f"MXNET_SPEC_K={self.k}: need >= 1")
        self.width = self.k + 1          # G: pending token + k drafts
        self.adapt = bool(adapt if adapt is not None
                          else util.getenv_bool("MXNET_SPEC_ADAPT"))
        floor = int(accept_floor_pct if accept_floor_pct is not None
                    else util.getenv_int("MXNET_SPEC_ACCEPT_FLOOR_PCT"))
        self.accept_floor = min(max(floor, 0), 100) / 100.0
        self._draft_factory = draft_factory
        self._params_np = {name: _np.asarray(v, _np.float32)
                           for name, v in predictor._param_vals.items()}
        self._compile_lock = _mxsan.lock(
            "serve/spec_decode.py", "self._compile_lock")
        self._verify_fn = None
        self._warm = False

    # -- draft ----------------------------------------------------------
    def make_draft(self, prompt):
        """Fresh per-stream draft state seeded with the prompt's KV."""
        if self._draft_factory is not None:
            return self._draft_factory(prompt)
        return DraftState(self._params_np, self.predictor.num_heads,
                          self.predictor.head_dim, prompt)

    # -- adaptive k -----------------------------------------------------
    def next_k(self, cur_k, ema):
        """Per-stream depth policy: shrink toward 1 below the accept
        floor, regrow toward the cap at sustained near-full acceptance,
        hold in between (hysteresis against oscillation)."""
        if not self.adapt or ema is None:
            return cur_k
        if ema < self.accept_floor:
            return max(1, cur_k - 1)
        if ema >= max(self.accept_floor, _GROW_AT):
            return min(self.k, cur_k + 1)
        return cur_k

    # -- the verify executable ------------------------------------------
    def _verify_key(self):
        p = self.predictor
        return (f"serve:verify[s{p.slots},g{self.width},"
                f"{p._geom_tag()}]")

    def _make_verify(self):
        p = self.predictor
        h_, d_, ps, p_, s_ = (p.num_heads, p.head_dim, p.page_size,
                              p.num_pages, p.slots)
        g_ = self.width
        e_ = p.embed

        def call(params, tokens, positions, k_pages, v_pages, page_tables):
            # tokens (S, G) int32 — row 0 the slot's pending token, rows
            # 1..k its drafts; positions (S, G) int32 write positions,
            # -1 = padding/idle row (write dropped, read clamped, output
            # ignored). Returns y (S, G): the target's greedy next token
            # after each row.
            import jax.numpy as jnp
            from ..parallel.paged_attention import paged_attention_multiquery
            active = positions >= 0
            pos = jnp.maximum(positions, 0)
            h = params["emb"][tokens]                       # (S, G, E)
            q = (h @ params["wq"]).reshape(s_, g_, h_, d_)
            k = (h @ params["wk"]).reshape(s_, g_, h_, d_)
            v = (h @ params["wv"]).reshape(s_, g_, h_, d_)
            row = jnp.arange(s_, dtype=jnp.int32)[:, None]
            flat = page_tables[row, pos // ps] * ps + pos % ps
            flat = jnp.where(active, flat, p_ * ps).reshape(s_ * g_)
            kp = k_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                k.reshape(s_ * g_, h_, d_),
                mode="drop").reshape(p_, ps, h_, d_)
            vp = v_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                v.reshape(s_ * g_, h_, d_),
                mode="drop").reshape(p_, ps, h_, d_)
            attn = paged_attention_multiquery(q, kp, vp, page_tables,
                                              pos + 1)
            o = attn.reshape(s_, g_, e_) @ params["wo"] + h
            logits = o @ params["w_out"]                    # (S, G, V)
            y = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return y, kp, vp

        return call

    def _exec_verify(self):
        with self._compile_lock:
            if self._verify_fn is None:
                from .. import compile_cache as _cc
                self._verify_fn = _cc.cached_jit(self._verify_key(),
                                                 self._make_verify())
        return self._verify_fn

    def warmup(self):
        """AOT-compile THE verify executable. Returns {"verify": kind}
        with kind in {"hit", "disk", "miss"} — a warm boot against a
        populated MXNET_EXEC_CACHE_DIR reports no "miss"."""
        import jax
        import jax.numpy as jnp
        p = self.predictor
        i32 = jnp.int32
        kv = jax.ShapeDtypeStruct((p.num_pages, p.page_size, p.num_heads,
                                   p.head_dim), jnp.float32)
        sg = jax.ShapeDtypeStruct((p.slots, self.width), i32)
        fn = self._exec_verify()
        kind = fn.warmup(
            p._param_vals, sg, sg, kv, kv,
            jax.ShapeDtypeStruct((p.slots, p.max_pages_per_seq), i32))
        self._warm = True
        return {"verify": kind}

    @property
    def is_warm(self):
        return self._warm

    # -- runtime entry point (called by the scheduler loop) -------------
    def verify(self, tokens, positions, k_pages, v_pages, page_tables,
               traces=()):
        """One batched verify dispatch over all slots x G rows.

        ``traces`` optionally carries the reqtrace contexts of the streams
        riding this batch; when non-empty each gets a ``spec_verify`` span
        covering the shared dispatch (same wall interval, per-request id).
        """
        import time as _time

        import jax.numpy as jnp
        fn = self._exec_verify()
        t0 = _time.perf_counter()
        y, kp, vp = fn(self.predictor._param_vals,
                       jnp.asarray(tokens, jnp.int32),
                       jnp.asarray(positions, jnp.int32),
                       k_pages, v_pages,
                       jnp.asarray(page_tables, jnp.int32))
        out = _np.asarray(y)
        if traces:
            from . import reqtrace as _rt
            dur_ms = (_time.perf_counter() - t0) * 1e3
            for ctx in traces:
                _rt.observe(ctx, "spec_verify", dur_ms, t0=t0,
                            args={"width": self.width,
                                  "batch": int(len(out))})
        self._warm = True
        return out, kp, vp
