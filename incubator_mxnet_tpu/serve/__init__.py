"""Predict-only inference serving (reference c_predict ABI, grown into a
serving subsystem).

Reference: include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc —
a training-free Predictor over an exported (symbol.json, .params) pair.
On top of that surface this package adds what production TPU serving
actually needs (TensorFlow paper §Serving; Ragged Paged Attention's
shape-bucketing discipline):

  predictor.py  Predictor — cached per-bucket jit executables over the
                exported graph; reference-compatible .params input.
  batcher.py    DynamicBatcher — coalesce concurrent requests into a
                fixed ladder of padded bucket shapes (max-latency +
                max-batch triggers), bounded admission queue, load-shed
                with retryable errors.
  server.py     ModelServer — stdlib threaded HTTP front end mapping the
                shed/deadline policy onto 503/504.
  stats.py      ServingStats — p50/p95/p99 histograms, queue/shed/
                occupancy counters, published via profiler.Counter so
                profiler.dumps() shows the serving table.
  control_plane.py  ServeRegistry / ReplicaAgent / RolloutManager —
                coordinator-side replica registry over the kvstore v2
                wire, replica-side heartbeat agent, and zero-downtime
                generation rollout with SLO-gated automatic rollback.
  router.py     Router — client-side load balancing across ready
                replicas with deadlines, jittered retries, hedged
                requests, and per-replica circuit breakers.
  decode.py     DecodeScheduler / DecodePredictor / PageAllocator —
                continuous-batching autoregressive decode: iteration-
                level admit/retire over a fixed slot batch, paged
                KV-cache (free-list pages + per-sequence page tables),
                AOT-warmed prefill buckets + ONE decode executable,
                streamed per-token through ModelServer's /generate.
  prefix_cache.py  PrefixCache — radix tree over token prefixes mapping
                to refcounted KV pages: a shared prefix is admitted
                read-only through PageAllocator.share and copy-on-write
                forked on first divergent write; LRU leaves evict only
                at refcount 0.
  disagg.py     PrefillPredictor / PrefillEngine — disaggregated
                prefill/decode serving: chunked prefill (one executable,
                traced offsets, decode steps interleave between chunks),
                page EXPORT on prefill-role replicas, KV-page shipping
                over the MAC'd kvstore wire, and kv_import admission on
                decode-role replicas; replica roles flow through the
                ServeRegistry to the role-aware Router.
  spec_decode.py  SpecDecoder / DraftState — speculative decoding on
                the fixed-shape decode path: a cheap self-draft
                proposes k tokens per stream per iteration and ONE
                batched-verify executable (multi-query paged attention)
                scores every proposal in a single target step;
                longest-agreeing-prefix acceptance keeps greedy streams
                bit-identical to plain decode while amortizing dispatch
                over k+1 tokens. Per-stream adaptive k from an
                accept-rate EMA; MXNET_SPEC_DECODE / MXNET_SPEC_K.
  reqtrace.py   RequestTrace — end-to-end request tracing and TTFT
                budget attribution across the disaggregated plane: a
                W3C-traceparent-style context minted at the router,
                propagated in the X-MXNET-Trace header through /prefill
                and /generate and inside the MAC'd kvstore wire's v2
                envelope, booking per-hop spans through the profiler
                timeline so tools/trace_merge.py stitches one request
                across router/prefill/decode processes. Head sampling +
                a tail-exemplar ring (errors and SLO breaches always
                kept), /debugz/requests, and mxnet_reqtrace_* Prometheus
                exemplars. MXNET_REQTRACE / _SAMPLE / _RING.

Typical use::

    import incubator_mxnet_tpu as mx
    net.export("model")                       # training side
    pred = mx.serve.Predictor.from_artifact("model",
                                            bucket_sizes=(4, 8, 16, 32))
    with mx.serve.ModelServer(pred, port=8080) as srv:
        ...                                   # POST /predict
"""
from .predictor import BucketLadder, Predictor
from .batcher import DeadlineExceeded, DynamicBatcher, Overloaded
from .server import ModelServer
from .stats import LatencyHistogram, ServingStats
from .control_plane import ReplicaAgent, RolloutManager, ServeRegistry
from .router import NoReplicaAvailable, RouteError, Router, RouterStats
from .decode import (DecodePredictor, DecodeScheduler, DecodeStream,
                     PageAllocator)
from .prefix_cache import PrefixCache
from .disagg import (PrefillEngine, PrefillPredictor, fetch_kv_import,
                     ship_key_for)
from .spec_decode import DraftState, SpecDecoder
from . import reqtrace
from .reqtrace import RequestTrace

__all__ = ["Predictor", "BucketLadder", "DynamicBatcher", "ModelServer",
           "ServingStats", "LatencyHistogram", "Overloaded",
           "DeadlineExceeded", "ServeRegistry", "ReplicaAgent",
           "RolloutManager", "Router", "RouterStats", "RouteError",
           "NoReplicaAvailable", "DecodePredictor", "DecodeScheduler",
           "DecodeStream", "PageAllocator", "PrefixCache",
           "PrefillPredictor", "PrefillEngine", "ship_key_for",
           "fetch_kv_import", "SpecDecoder", "DraftState",
           "reqtrace", "RequestTrace"]
