"""Request-scoped tracing across the disaggregated serving plane.

The fleet telemetry from earlier rounds (phase histograms, serving
quantiles) explains *populations* of requests; this module explains one
request.  A ``RequestTrace`` context — W3C-traceparent-style trace id,
root span id, sampled bit, and deadline baggage — is minted at
``Router.generate``/``Router.request``, propagated over HTTP in the
``X-MXNET-Trace`` header through ``/prefill`` and ``/generate`` (every
retry and hedge attempt a distinct child span), and rides the v2
``{trace, span}`` envelope inside the MAC'd kvstore wire for
``kv_page_put``/``kv_page_get``, so a single trace id stitches the
router, prefill, and decode processes together.

Each hop books chrome-trace X spans through the profiler StepTimeline
machinery (``router_queue``, ``route_attempt#n``, ``hedge``,
``prefill_chunk``, ``kv_ship``, ``decode_admission``, ``first_step``,
``spec_verify``) carrying ``req_trace``/``req_span``/``req_parent``
args that ``tools/trace_merge.py`` joins onto the shared wall clock and
``tools/validate_trace.py`` schema-checks.

Gate discipline (the PR-10/11 cached-bool idiom): everything here is
behind ``MXNET_REQTRACE``.  With the gate off, ``mint`` returns None,
``span``/``span_for`` return a shared null span, no header is attached,
the kvstore wire frame stays the plain pickled tuple (byte-identical to
a build without this module), and ``record_count()`` stays exactly 0 —
tests assert the counter, not just wall-clock deltas.  Head sampling is
per-mille via ``MXNET_REQTRACE_SAMPLE``; a bounded tail-exemplar ring
(``MXNET_REQTRACE_RING``) always promotes error or SLO-breaching
requests even when head sampling skipped them, and is exposed at
``/debugz/requests`` and joined to flight-recorder postmortems via the
trace id carried on breadcrumbs.

Lock hierarchy: the module ``_lock`` is a leaf — it guards the record
counter and the exemplar rings and is never held across profiler, I/O,
or other-module calls (``lock_order.py`` declares this).  Span booking
takes ``profiler._lock`` internally *after* ``_lock`` is released.

See ``docs/architecture/note_request_tracing.md``.
"""

from __future__ import annotations

import collections
import random
import threading
import time

from .. import profiler as _prof
from ..util import getenv_bool, getenv_int
from .. import mxsan as _mxsan

__all__ = [
    "TRACE_HEADER", "RequestTrace", "enabled", "enable", "reset",
    "record_count", "mint", "activate", "current", "current_trace_id",
    "span", "span_for", "observe", "attempt", "finish", "promote",
    "wire_fields",
    "to_header", "from_header", "ring_snapshot", "slowest",
    "render_prometheus",
]

TRACE_HEADER = "X-MXNET-Trace"

_lock = _mxsan.lock(
    "serve/reqtrace.py", "_lock")        # leaf: counter + rings only
_tls = threading.local()        # .ctx = active RequestTrace, .stack = span ids

_enabled = None                 # cached MXNET_REQTRACE bool (None = unread)
_records = 0                    # spans + ring rows booked; 0 while gate off
_requests = 0                   # finish() calls (the per-request counter)
_ring = None                    # deque of recent sampled request summaries
_exemplars = None               # deque of error / SLO-breach promotions
_rng = random.Random()          # head-sampling dice (per-process)


# ---------------------------------------------------------------------------
# gate (cached bool, force-override for tests, reset forgets everything)
# ---------------------------------------------------------------------------

def enabled():
    """Cached ``MXNET_REQTRACE`` gate — the env var is read once."""
    global _enabled
    if _enabled is None:
        _enabled = getenv_bool("MXNET_REQTRACE")
    return _enabled


def enable(on=True):
    """Force the gate (tests / diagnose probes). Returns the previous
    cached value (None if the env var had not been consulted yet)."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def reset():
    """Forget the cached gate and drop all tracing state."""
    global _enabled, _records, _requests, _ring, _exemplars
    with _lock:
        _enabled = None
        _records = 0
        _requests = 0
        _ring = None
        _exemplars = None


def record_count():
    """Total reqtrace records booked (spans + ring rows). Exactly 0 while
    the gate is off — the zero-overhead assert counts records, it does
    not time anything."""
    with _lock:
        return _records


# ---------------------------------------------------------------------------
# context
# ---------------------------------------------------------------------------

class RequestTrace:
    """One request's identity: 128-bit trace id, the root span id minted
    alongside it, the head-sampling decision, and deadline baggage."""

    __slots__ = ("trace_id", "span_id", "sampled", "deadline_ms",
                 "baggage", "t0", "first_token_t", "budget")

    def __init__(self, trace_id, span_id, sampled, deadline_ms=None,
                 baggage=None):
        self.trace_id = trace_id
        self.span_id = int(span_id)
        self.sampled = bool(sampled)
        self.deadline_ms = deadline_ms
        self.baggage = dict(baggage) if baggage else {}
        self.t0 = time.perf_counter()
        self.first_token_t = None
        self.budget = None      # done-row TTFT breakdown, once known

    def mark_first_token(self):
        if self.first_token_t is None:
            self.first_token_t = time.perf_counter()

    @property
    def ttft_ms(self):
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.t0) * 1e3

    def __repr__(self):
        return (f"RequestTrace({self.trace_id}, span={self.span_id}, "
                f"sampled={self.sampled})")


def mint(deadline_ms=None):
    """Mint a new request context at the router edge. Returns None when
    the gate is off. The head-sampling decision (``MXNET_REQTRACE_SAMPLE``
    per-mille) is taken here and travels with the context: an unsampled
    request emits no spans anywhere, but still carries an id so the
    tail-exemplar ring can promote it if it errors or breaches SLO."""
    if not enabled():
        return None
    per_mille = max(0, min(1000, getenv_int("MXNET_REQTRACE_SAMPLE")))
    sampled = _rng.randrange(1000) < per_mille
    return RequestTrace(f"{_rng.getrandbits(128):032x}",
                        _prof.next_span_id(), sampled,
                        deadline_ms=deadline_ms)


class _Activation:
    __slots__ = ("ctx", "prev")

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        self.prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        _tls.ctx = self.prev
        return False


def activate(ctx):
    """Make ``ctx`` the thread's active request context for the duration
    of the with-block. ``ctx`` may be None (deactivates)."""
    return _Activation(ctx)


def current():
    """The thread's active RequestTrace, or None."""
    if not enabled():
        return None
    return getattr(_tls, "ctx", None)


def current_trace_id():
    """Trace id of the active context (sampled or not) — breadcrumb
    helper so flight-recorder rows can join a postmortem to the request
    trace. None when the gate is off or no context is active."""
    ctx = current()
    return None if ctx is None else ctx.trace_id


# ---------------------------------------------------------------------------
# header codec (W3C traceparent-shaped, plus `;k=v` baggage)
# ---------------------------------------------------------------------------

def to_header(ctx, **baggage):
    """``00-<trace32>-<span16>-<flags>`` plus ``;key=value`` baggage.
    Numeric baggage (deadline_ms, router_ms, prefill_ms, ship_ms) is
    rendered with millisecond precision to 3 decimals."""
    flags = "01" if ctx.sampled else "00"
    parts = [f"00-{ctx.trace_id}-{ctx.span_id & 0xffffffffffffffff:016x}"
             f"-{flags}"]
    items = {}
    if ctx.deadline_ms is not None:
        items["deadline_ms"] = ctx.deadline_ms
    items.update(ctx.baggage)
    items.update({k: v for k, v in baggage.items() if v is not None})
    for k in sorted(items):
        v = items[k]
        parts.append(f"{k}={v:.3f}" if isinstance(v, float) else f"{k}={v}")
    return ";".join(parts)


def from_header(value):
    """Parse an ``X-MXNET-Trace`` header back into a RequestTrace.
    Malformed input returns None — tracing never breaks serving."""
    if not value or not enabled():
        return None
    try:
        fields = value.split(";")
        ver, tid, sid, flags = fields[0].split("-")
        if ver != "00" or len(tid) != 32 or len(sid) != 16:
            return None
        int(tid, 16)
        baggage, deadline = {}, None
        for item in fields[1:]:
            k, _, v = item.partition("=")
            if not _ or not k:
                return None
            if k == "deadline_ms":
                deadline = float(v)
            else:
                try:
                    baggage[k] = float(v)
                except ValueError:
                    baggage[k] = v
        return RequestTrace(tid, int(sid, 16), int(flags, 16) & 1,
                            deadline_ms=deadline, baggage=baggage)
    except (ValueError, IndexError):
        return None


def wire_fields():
    """Header dict fields for the kvstore v2 envelope — ``req_trace`` and
    ``req_span`` — or None when there is nothing to propagate. The caller
    (kvstore_server.AsyncClient) only wraps the frame when this (or step
    attribution) is active, keeping the gate-off wire byte-identical."""
    if not enabled():
        return None
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or not ctx.sampled:
        return None
    stack = getattr(_tls, "stack", None)
    return {"req_trace": ctx.trace_id,
            "req_span": stack[-1] if stack else ctx.span_id}


# ---------------------------------------------------------------------------
# span emission (books through the profiler StepTimeline machinery)
# ---------------------------------------------------------------------------

class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("ctx", "phase", "args", "t0", "sid", "parent")

    def __init__(self, ctx, phase, args):
        self.ctx = ctx
        self.phase = str(phase)
        self.args = args

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self.parent = stack[-1] if stack else None
        self.sid = _prof.next_span_id()
        stack.append(self.sid)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur_ms = (time.perf_counter() - self.t0) * 1e3
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] == self.sid:
            stack.pop()
        _emit(self.ctx, self.phase, self.t0, dur_ms, self.sid,
              self.parent, self.args)
        return False


def span(phase, args=None):
    """Context-managed span against the thread's active request context.
    Shared null span (no allocation beyond one _Span) when the gate is
    off, no context is active, or the request is head-unsampled."""
    if not enabled():
        return _NULL_SPAN
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or not ctx.sampled:
        return _NULL_SPAN
    return _Span(ctx, phase, args)


def span_for(ctx, phase, args=None):
    """Explicit-context span for threads where the request is not
    thread-local active (the decode scheduler loop owns many streams)."""
    if ctx is None or not ctx.sampled or not enabled():
        return _NULL_SPAN
    return _Span(ctx, phase, args)


def observe(ctx, phase, dur_ms, t0=None, args=None):
    """Book an externally measured request span (the queue-wait /
    first-step pattern: the interval was measured by other code)."""
    if ctx is None or not ctx.sampled or not enabled():
        return
    if t0 is None:
        t0 = time.perf_counter() - dur_ms / 1e3
    _emit(ctx, str(phase), t0, float(dur_ms), _prof.next_span_id(),
          None, args)


def attempt(ctx, n, cause, dur_ms, t0=None, hedged=False, replica=None):
    """One router attempt as a child span: ``route_attempt#<n>`` with a
    ``cause`` arg (ok, connect-error, 503-shed, hedge-win, ...) so
    discarded-attempt accounting is trace-visible."""
    args = {"cause": str(cause)}
    if hedged:
        args["hedged"] = True
    if replica is not None:
        args["replica"] = replica
    observe(ctx, f"route_attempt#{int(n)}", dur_ms, t0=t0, args=args)


def _emit(ctx, phase, t0, dur_ms, sid, parent, args):
    global _records
    extra = {"req_trace": ctx.trace_id, "req_span": sid,
             "req_parent": parent if parent is not None else ctx.span_id}
    if ctx.deadline_ms is not None:
        extra["deadline_ms"] = ctx.deadline_ms
    if args:
        extra.update(args)
    # parent links stay local to this process: cross-process lineage is
    # expressed via req_parent (the minted root span id), never via the
    # profiler's containment-checked `parent` arg
    _prof.request_phase(phase, t0, dur_ms, sid, parent, extra)
    with _lock:
        _records += 1


# ---------------------------------------------------------------------------
# tail-exemplar ring
# ---------------------------------------------------------------------------

def _rings_locked():
    global _ring, _exemplars
    if _ring is None:
        cap = max(getenv_int("MXNET_REQTRACE_RING"), 4)
        _ring = collections.deque(maxlen=cap)
        _exemplars = collections.deque(maxlen=cap)
    return _ring, _exemplars


def finish(ctx, status="ok", cause=None, ttft_ms=None, total_ms=None,
           budget=None, slo_ms=None):
    """Record a request outcome. Sampled requests land in the recent
    ring; error or SLO-breaching requests are *always* promoted to the
    exemplar ring, head sampling notwithstanding — the tail is exactly
    what aggregate histograms cannot explain."""
    global _records, _requests
    if ctx is None or not enabled():
        return
    breach = bool(slo_ms is not None and ttft_ms is not None
                  and ttft_ms > slo_ms)
    rec = {"trace": ctx.trace_id, "status": str(status), "t": time.time(),
           "sampled": ctx.sampled}
    if cause is not None:
        rec["cause"] = str(cause)
    if ttft_ms is not None:
        rec["ttft_ms"] = round(float(ttft_ms), 3)
    if total_ms is not None:
        rec["total_ms"] = round(float(total_ms), 3)
    if budget is not None:
        rec["budget"] = dict(budget)
    if ctx.deadline_ms is not None:
        rec["deadline_ms"] = ctx.deadline_ms
    if breach:
        rec["slo_breach"] = True
    with _lock:
        ring, exemplars = _rings_locked()
        if ctx.sampled:
            ring.append(rec)
        if status != "ok" or breach:
            exemplars.append(rec)
        _records += 1
        _requests += 1


def promote(ctx, cause, detail=None):
    """Promote a failed ATTEMPT to the exemplar ring immediately, head
    sampling notwithstanding. A whole-stream retry may still win the
    request, but the kill -9 postmortem on the replica that cut the
    stream needs this row to join the trace — waiting for the request's
    final outcome would lose the evidence."""
    global _records
    if ctx is None or not enabled():
        return
    rec = {"trace": ctx.trace_id, "status": "error", "cause": str(cause),
           "t": time.time(), "sampled": ctx.sampled,
           "elapsed_ms": round((time.perf_counter() - ctx.t0) * 1e3, 3)}
    if detail is not None:
        rec["detail"] = str(detail)[:200]
    with _lock:
        _, exemplars = _rings_locked()
        exemplars.append(rec)
        _records += 1


def ring_snapshot():
    """The ``/debugz/requests`` payload: both rings plus occupancy."""
    with _lock:
        if _ring is None:
            return {"enabled": bool(_enabled), "capacity": 0,
                    "recent": [], "exemplars": []}
        return {"enabled": bool(_enabled), "capacity": _ring.maxlen,
                "recent": list(_ring), "exemplars": list(_exemplars)}


def slowest(k=5):
    """Slowest-k finished requests across both rings (dedup by trace id,
    sorted by total_ms falling back to ttft_ms) — the diagnose view."""
    snap = ring_snapshot()
    by_trace = {}
    for rec in snap["recent"] + snap["exemplars"]:
        by_trace[rec["trace"]] = rec
    key = lambda r: r.get("total_ms") or r.get("ttft_ms") or 0.0  # noqa: E731
    return sorted(by_trace.values(), key=key, reverse=True)[:max(int(k), 0)]


def render_prometheus(labels=""):
    """``mxnet_reqtrace_*`` text-format families. Conditional like the
    spec-decode families: empty string until the first record exists, so
    a gate-off scrape is byte-identical to earlier rounds."""
    with _lock:
        records, requests = _records, _requests
        recent = len(_ring) if _ring is not None else 0
        exemplars = len(_exemplars) if _exemplars is not None else 0
        cap = _ring.maxlen if _ring is not None else 0
    if records == 0:
        return ""
    lab = f"{{{labels}}}" if labels else ""
    lines = [
        "# TYPE mxnet_reqtrace_records_total counter",
        f"mxnet_reqtrace_records_total{lab} {records}",
        "# TYPE mxnet_reqtrace_requests_total counter",
        f"mxnet_reqtrace_requests_total{lab} {requests}",
        "# TYPE mxnet_reqtrace_ring_occupancy gauge",
    ]
    sep = "," if labels else ""
    lines.append(f'mxnet_reqtrace_ring_occupancy{{{labels}{sep}'
                 f'ring="recent"}} {recent}')
    lines.append(f'mxnet_reqtrace_ring_occupancy{{{labels}{sep}'
                 f'ring="exemplar"}} {exemplars}')
    lines.append("# TYPE mxnet_reqtrace_ring_capacity gauge")
    lines.append(f"mxnet_reqtrace_ring_capacity{lab} {cap}")
    return "\n".join(lines) + "\n"
