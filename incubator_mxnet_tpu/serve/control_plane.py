"""Fleet serving control plane: replica registry, heartbeat agent,
zero-downtime rollouts.

The reference framework stopped at a single predict process (c_predict
embedded into user binaries); the fleet shape — N ModelServer replicas
behind a liveness-checked coordinator, rolling weight updates with zero
failed requests — composes three planes this repo already ships:

* the MAC'd dist_async wire (kvstore_server.py) carries registration:
  replicas ``serve_register (model, generation, buckets, http_addr)``
  with the coordinator and refresh liveness + readiness with
  ``serve_beat``, so replica membership inherits the cluster trust
  boundary instead of inventing a second discovery protocol;
* the AOT executable cache (compile_cache + ``Predictor.warmup``)
  defines READINESS: a replica advertises ready only when every ladder
  bucket is warm, so the router never sends traffic into an XLA trace;
* the fleet observability plane (fleetobs.py) defines HEALTH
  (``LIVE_WINDOW`` liveness from heartbeat age) and gates rollouts (the
  SLO burn-rate engine firing during a wave triggers auto-rollback).

Three roles live here:

``ServeRegistry``   coordinator-side table of serving replicas, owned by
                    AsyncServer (lazily, like its FleetRegistry) and
                    exposed over the serve_* wire ops.
``ReplicaAgent``    replica-side registration + heartbeat loop wrapping
                    a ModelServer; deregisters on drain.
``RolloutManager``  operator-side zero-downtime weight update: prewarm
                    the new generation against the disk cache, shift
                    traffic in waves through each replica's drain-swap
                    admin endpoint, consult the SLO gate between waves,
                    roll every updated replica back if it fires.

Lock discipline: each role has one instance ``self._lock``; the module
``_lock`` guarding the counter registry is a LEAF (never held while
calling out). Flight-recorder breadcrumbs and counter bumps happen
AFTER instance locks are released (the fleetobs discipline).
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request

from .. import fault as _fault
from ..base import MXNetError
from ..util import getenv_bool, getenv_int
from .. import mxsan as _mxsan

__all__ = ["ServeRegistry", "ReplicaAgent", "RolloutManager"]

_log = logging.getLogger("incubator_mxnet_tpu.serve.control_plane")

# -- module counter registry (diagnose.py Control Plane section) -----------
_lock = _mxsan.lock("serve/control_plane.py", "_lock")
_counters = {
    "registrations": 0,         # serve_register ops handled
    "deregistrations": 0,       # serve_deregister ops handled
    "beats": 0,                 # serve_beat ops handled
    "rollouts_started": 0,      # RolloutManager.rollout entered
    "rollout_waves": 0,         # waves completed (incl. the one rolled back)
    "rollout_replicas_updated": 0,
    "rollout_replica_failures": 0,  # reload attempts that errored
    "rollbacks": 0,             # SLO-gated automatic rollbacks
    "graceful_shutdowns": 0,    # ModelServer drain-then-stop sequences
}


def _bump(name, n=1):
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def stats():
    with _lock:
        return dict(_counters)


def clear():
    with _lock:
        for k in _counters:
            _counters[k] = 0


def _live_window_s():
    from .. import fleetobs as _fobs
    return _fobs.FleetRegistry.LIVE_WINDOW_S


def _http_json(addr, path, payload=None, timeout=10.0):
    """Tiny JSON-over-HTTP helper for replica admin endpoints. Raises
    urllib.error.HTTPError (status) / URLError (connect) on failure."""
    data = None if payload is None else json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        f"http://{addr}{path}", data=data,
        headers={"Content-Type": "application/json"},
        method="POST" if data is not None else "GET")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


# ---------------------------------------------------------------------------
# coordinator side
# ---------------------------------------------------------------------------

class ServeRegistry:
    """Coordinator-side serving-replica table.

    One row per (model, replica_id): generation, bucket ladder, HTTP
    address, readiness (replica-reported: warm + registered + not
    draining) and liveness (beat age within the fleetobs LIVE_WINDOW,
    judged by THIS host's monotonic clock — same rule as the training
    liveness registry). ``view()`` is what routers poll; it never blocks
    on anything but the registry lock.
    """

    def __init__(self, live_window_s=None):
        self._lock = _mxsan.lock("serve/control_plane.py", "self._lock")
        self._replicas = {}     # (model, rid) -> row dict
        self._next_id = 0
        self._epoch = 0         # bumps on register/deregister
        self._live_window = (live_window_s if live_window_s is not None
                             else _live_window_s())

    def register(self, model, replica_id, generation, buckets, http_addr,
                 role="both"):
        role = str(role or "both")
        if role not in ("prefill", "decode", "both"):
            raise MXNetError(f"replica role {role!r}: want "
                             f"prefill|decode|both")
        with self._lock:
            if replica_id is None:
                replica_id = f"r{self._next_id}"
                self._next_id += 1
            self._replicas[(model, replica_id)] = {
                "generation": int(generation),
                "buckets": tuple(int(b) for b in (buckets or ())),
                "http_addr": str(http_addr),
                "role": role,
                "load": {},         # latest beat's load report
                "ready": False,     # readiness arrives with the first beat
                "draining": False,
                "seen_mono": time.monotonic(),
            }
            self._epoch += 1
            epoch = self._epoch
        _bump("registrations")
        _fault.flight_record("serve_register", model=model,
                             replica=replica_id, generation=int(generation),
                             http_addr=str(http_addr), role=role)
        return {"replica_id": replica_id, "epoch": epoch}

    def beat(self, model, replica_id, generation, ready, draining=False,
             load=None):
        with self._lock:
            row = self._replicas.get((model, replica_id))
            if row is None:
                # coordinator restarted / replica predates this registry:
                # tell the agent to re-register (it keeps its id)
                return {"registered": False, "epoch": self._epoch}
            row["generation"] = int(generation)
            row["ready"] = bool(ready)
            row["draining"] = bool(draining)
            if load is not None:
                row["load"] = dict(load)
            row["seen_mono"] = time.monotonic()
            epoch = self._epoch
        _bump("beats")
        return {"registered": True, "epoch": epoch}

    def deregister(self, model, replica_id):
        with self._lock:
            row = self._replicas.pop((model, replica_id), None)
            if row is not None:
                self._epoch += 1
            epoch = self._epoch
        if row is not None:
            _bump("deregistrations")
            _fault.flight_record("serve_deregister", model=model,
                                 replica=replica_id)
        return {"removed": row is not None, "epoch": epoch}

    def view(self, model=None):
        """Routing view: every row plus computed ``live`` and ``age_s``.
        model=None returns all models (operator surface)."""
        now = time.monotonic()
        with self._lock:
            replicas = {}
            for (m, rid), row in self._replicas.items():
                if model is not None and m != model:
                    continue
                age = now - row["seen_mono"]
                replicas[rid] = {
                    "model": m,
                    "generation": row["generation"],
                    "buckets": list(row["buckets"]),
                    "http_addr": row["http_addr"],
                    "role": row.get("role", "both"),
                    "load": dict(row.get("load") or {}),
                    "ready": row["ready"],
                    "draining": row["draining"],
                    "live": age <= self._live_window,
                    "age_s": round(age, 3),
                }
            return {"epoch": self._epoch, "replicas": replicas}


# ---------------------------------------------------------------------------
# replica side
# ---------------------------------------------------------------------------

class ReplicaAgent:
    """Registers a ModelServer with the coordinator and keeps beating.

    The beat carries (generation, ready, draining) — readiness is the
    server's composite gate (every bucket AOT-warm, registered, not
    draining), so the registry's view and the replica's /readyz endpoint
    answer from the same truth. A beat answered with registered=False
    (coordinator restart) re-registers under the same replica_id. The
    loop never crashes on a missed beat — like the training heartbeat
    sender, missed beats ARE the death signal.
    """

    def __init__(self, server, coordinator, model="default", period_s=None):
        self._server = server
        self._coordinator = coordinator     # "addr token" string
        self.model = model
        self._period = (period_s if period_s is not None
                        else max(1, getenv_int("MXNET_HEARTBEAT_INTERVAL")))
        self.replica_id = None
        self.registered = False
        self._lock = _mxsan.lock(
            "serve/control_plane.py", "self._lock")       # guards the wire client handle
        self._client = None
        self._stop = threading.Event()
        self._thread = None

    def _client_locked(self):
        if self._client is None:
            from .. import kvstore_server as _ksrv
            self._client = _ksrv.connect_async_server(self._coordinator)
        return self._client

    def _drop_client_locked(self):
        if self._client is not None:
            self._client.close()
            self._client = None

    def register(self):
        srv = self._server
        host, port = srv.address
        with self._lock:
            reply = self._client_locked().call(
                "serve_register", self.model, self.replica_id,
                srv.generation, list(srv.buckets), f"{host}:{port}",
                getattr(srv, "role", "both"))
        self.replica_id = reply["replica_id"]
        self.registered = True
        return reply

    def beat_now(self):
        """One beat; re-registers first if the coordinator forgot us.
        v2 beats append the server's load report (KV page headroom) so
        the router can place decode streams by memory, not just
        round-robin."""
        srv = self._server
        load = getattr(srv, "load_report", None)
        load = load() if callable(load) else None
        with self._lock:
            reply = self._client_locked().call(
                "serve_beat", self.model, self.replica_id,
                srv.generation, srv.ready, srv.draining, load)
        if not reply.get("registered", True):
            self.register()
            self.beat_now()
        return reply

    def start(self):
        self.register()
        self.beat_now()     # readiness lands before the first period
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="mxtpu-serve-agent", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self._period):
            try:
                self.beat_now()
            except (MXNetError, OSError, ConnectionError):
                # coordinator unreachable this beat: drop the connection
                # and redial next period
                with self._lock:
                    self._drop_client_locked()

    def stop(self, deregister=True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if deregister and self.registered:
            try:
                with self._lock:
                    self._client_locked().call(
                        "serve_deregister", self.model, self.replica_id)
            except (MXNetError, OSError, ConnectionError):
                pass
            self.registered = False
        with self._lock:
            self._drop_client_locked()


# ---------------------------------------------------------------------------
# operator side: zero-downtime rollout
# ---------------------------------------------------------------------------

class RolloutManager:
    """Wave-based zero-downtime weight rollout with an SLO rollback gate.

    State machine (every transition leaves a flight-recorder breadcrumb
    and is visible in ``render_prometheus()`` / ``history``)::

        idle -> started -> wave[i] -> settling -> wave[i+1] -> ... -> done
                                          |
                                          v (SLO engine firing)
                                    rolling_back -> rolled_back

    Per replica the shift is delegated to the ModelServer's
    ``/admin/reload`` endpoint, whose sequence IS the zero-downtime
    contract: prewarm the new generation's executables from the disk
    cache (no traffic touched), then drain the old generation through
    the batcher's admission control (pause -> quiesce), swap, resume —
    requests arriving in the milliseconds of drain get retryable 503s
    the router reroutes.

    A replica that is UNREACHABLE during its wave (the kill -9 chaos
    case) is skipped, counted, and left to the liveness registry — a
    dead replica must not abort a rollout. A replica that ANSWERS with
    a reload error is a bad generation signal and triggers rollback,
    same as the SLO gate.
    """

    STATES = ("idle", "started", "wave", "settling", "done",
              "rolling_back", "rolled_back")

    def __init__(self, coordinator, model="default", wave_size=None,
                 slo_check=None, settle_s=None, reload_timeout_s=60.0):
        self._coordinator = coordinator
        self.model = model
        self._wave_size = max(1, wave_size if wave_size is not None
                              else getenv_int("MXNET_ROLLOUT_WAVE_SIZE"))
        self._settle = (settle_s if settle_s is not None
                        else getenv_int("MXNET_ROLLOUT_SETTLE_MS") / 1e3)
        self._reload_timeout = reload_timeout_s
        self._slo_check = slo_check
        self._lock = _mxsan.lock(
            "serve/control_plane.py", "self._lock")   # guards state/history/counters
        self.state = "idle"
        self.generation = None
        self.history = []               # [(monotonic, state, info)]
        self._counts = {"waves_total": 0, "replicas_updated_total": 0,
                        "replica_failures_total": 0, "rollbacks_total": 0,
                        "slo_gate_checks_total": 0}
        self._client = None

    # -- wire/client helpers -------------------------------------------
    def _client_handle(self):
        if self._client is None:
            from .. import kvstore_server as _ksrv
            self._client = _ksrv.connect_async_server(self._coordinator)
        return self._client

    def _set_state(self, state, **info):
        with self._lock:
            self.state = state
            self.history.append((time.monotonic(), state, info))
        _fault.flight_record("rollout", state=state, model=self.model,
                             **info)
        _log.info("rollout[%s] -> %s %s", self.model, state, info or "")

    def _count(self, name, n=1):
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def _slo_firing(self):
        """Names of firing SLO alerts gating the next wave."""
        self._count("slo_gate_checks_total")
        if self._slo_check is not None:
            return list(self._slo_check())
        if not getenv_bool("MXNET_ROLLOUT_SLO_GATE"):
            return []
        try:
            reply = self._client_handle().call("fleet_alerts")
        except (MXNetError, OSError, ConnectionError):
            return []       # no fleet plane -> no gate
        rows = reply.get("alerts", []) if isinstance(reply, dict) else reply
        return [a["spec"] for a in rows if a.get("state") == "firing"]

    # -- the rollout ----------------------------------------------------
    def rollout(self, params, generation):
        """Shift every live replica of ``model`` to ``params`` (a
        .params path readable by the replicas) as ``generation``.
        Returns a result dict; ``ok`` is False when the SLO gate (or a
        reload error) rolled the fleet back."""
        _bump("rollouts_started")
        view = self._client_handle().call("serve_view", self.model)
        targets = sorted(
            (rid, row) for rid, row in view["replicas"].items()
            if row["live"])
        if not targets:
            raise MXNetError(
                f"no live replicas registered for model {self.model!r}")
        self._set_state("started", generation=generation,
                        replicas=[rid for rid, _ in targets])
        self.generation = generation
        updated, skipped = [], []
        bad_generation = None
        waves = [targets[i:i + self._wave_size]
                 for i in range(0, len(targets), self._wave_size)]
        for wi, wave in enumerate(waves):
            _fault.inject("rollout")    # MXNET_FAULT_INJECT: rollout@n
            self._set_state("wave", wave=wi,
                            replicas=[rid for rid, _ in wave])
            for rid, row in wave:
                try:
                    resp = _http_json(
                        row["http_addr"], "/admin/reload",
                        {"params": params, "generation": generation},
                        timeout=self._reload_timeout)
                except urllib.error.HTTPError as e:
                    # the replica ANSWERED and refused: bad weights/config
                    # — a per-replica failure the gate must act on
                    self._count("replica_failures_total")
                    _bump("rollout_replica_failures")
                    bad_generation = f"replica {rid} reload failed: {e}"
                    break
                except (urllib.error.URLError, OSError,
                        ConnectionError) as e:
                    # unreachable (killed mid-wave): skip, liveness owns it
                    self._count("replica_failures_total")
                    _bump("rollout_replica_failures")
                    skipped.append(rid)
                    _log.warning("rollout[%s] replica %s unreachable "
                                 "(%s); skipping", self.model, rid, e)
                    continue
                updated.append((rid, row))
                self._count("replicas_updated_total")
                _bump("rollout_replicas_updated")
                cold = resp.get("cold_buckets") or []
                if cold:
                    _log.warning(
                        "rollout[%s] replica %s compiled buckets %s "
                        "(disk cache cold) — prewarm the cache to keep "
                        "rollouts retrace-free", self.model, rid, cold)
            self._count("waves_total")
            _bump("rollout_waves")
            # settle, then consult the gate before touching the next wave
            self._set_state("settling", wave=wi)
            if self._settle > 0:
                time.sleep(self._settle)
            firing = [] if bad_generation is None else [bad_generation]
            firing += self._slo_firing()
            if firing:
                return self._rollback(updated, firing, generation)
        self._set_state("done", generation=generation,
                        updated=[rid for rid, _ in updated],
                        skipped=skipped)
        return {"ok": True, "state": "done", "generation": generation,
                "updated": [rid for rid, _ in updated],
                "skipped": skipped}

    def _rollback(self, updated, firing, generation):
        self._set_state("rolling_back", alerts=firing,
                        replicas=[rid for rid, _ in updated])
        self._count("rollbacks_total")
        _bump("rollbacks")
        from .. import fleetobs as _fobs
        _fobs.rollout_alert("rollout_rollback", model=self.model,
                            generation=generation, alerts=firing)
        failed = []
        for rid, row in updated:
            try:
                _http_json(row["http_addr"], "/admin/rollback", {},
                           timeout=self._reload_timeout)
            except (urllib.error.URLError, OSError, ConnectionError):
                failed.append(rid)
        self._set_state("rolled_back", alerts=firing,
                        rollback_failed=failed)
        return {"ok": False, "state": "rolled_back", "alerts": firing,
                "generation": generation,
                "updated": [rid for rid, _ in updated],
                "rollback_failed": failed}

    # -- observability --------------------------------------------------
    def render_prometheus(self):
        """mxnet_rollout_* families (scraped live by tests/operators,
        e.g. through Router.start_metrics_http extra renderers)."""
        with self._lock:
            state = self.state
            counts = dict(self._counts)
            generation = self.generation
        lines = ["# HELP mxnet_rollout_state 1 for the rollout manager's "
                 "current state machine node",
                 "# TYPE mxnet_rollout_state gauge"]
        for s in self.STATES:
            lines.append(
                f'mxnet_rollout_state{{model="{self.model}",state="{s}"}} '
                f'{1 if s == state else 0}')
        lines += ["# HELP mxnet_rollout_generation target generation of "
                  "the most recent rollout",
                  "# TYPE mxnet_rollout_generation gauge",
                  f'mxnet_rollout_generation{{model="{self.model}"}} '
                  f'{-1 if generation is None else generation}']
        for name, val in sorted(counts.items()):
            fam = f"mxnet_rollout_{name}"
            lines += [f"# HELP {fam} rollout manager counter",
                      f"# TYPE {fam} counter",
                      f'{fam}{{model="{self.model}"}} {val}']
        return "\n".join(lines) + "\n"
