"""Dynamic micro-batcher with admission control.

Coalesces concurrent single-sample requests into padded batches on the
Predictor's bucket ladder — the TPU-serving discipline (Ragged Paged
Attention, arXiv:2604.15464; TF-Serving's BatchingSession): one compiled
executable per bucket, a max-latency trigger so a lone request never
waits longer than `max_latency_ms`, and a max-batch trigger so a full
bucket dispatches immediately.

Admission control is load-shed-first (the graceful-degradation idiom of
fault.py / bench.py's backend probes): the request queue is BOUNDED, an
overflowing submit fails fast with a distinct retryable error
(`Overloaded`) instead of queueing into collapse, and requests whose
deadline expired while queued are dropped before wasting a bucket slot
(`DeadlineExceeded`). Both carry `retryable=True` so front ends map them
to 503/504 rather than 500.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

import numpy as _np

from ..base import MXNetError
from .stats import ServingStats
from .. import mxsan as _mxsan

__all__ = ["DynamicBatcher", "Overloaded", "DeadlineExceeded"]


class Overloaded(MXNetError):
    """Admission queue full — shed, retry against another replica/later."""
    retryable = True
    status = 503


class DeadlineExceeded(MXNetError):
    """Request deadline passed before a result was produced."""
    retryable = True
    status = 504


class _Request:
    __slots__ = ("inputs", "future", "enqueue_t", "deadline")

    def __init__(self, inputs, deadline):
        self.inputs = inputs
        self.future = Future()
        self.enqueue_t = time.monotonic()
        self.deadline = deadline


_STOP = object()


class DynamicBatcher:
    """Batches `submit()`ed single-sample requests through a predictor.

    predict:      callable(dict name -> (B, ...) array) -> list of (B, ...)
                  arrays (e.g. `Predictor.predict`; must be thread-safe).
    buckets:      the predictor's ladder — dispatch pads up to the next
                  bucket and never exceeds the largest.
    max_latency_ms: oldest-request wait bound before a partial bucket
                  dispatches.
    max_queue:    admission bound; beyond it submit() raises Overloaded.
    default_deadline_ms: per-request deadline when submit passes none.

    Requests are dicts of UNBATCHED arrays (sample shape, no batch axis);
    results resolve to lists of per-sample output arrays. Mixed sample
    shapes are grouped by signature and dispatched as separate buckets
    (shape-bucketing, never one ragged batch).
    """

    def __init__(self, predict, buckets=(1, 2, 4, 8, 16, 32),
                 max_latency_ms=5.0, max_queue=128,
                 default_deadline_ms=None, stats=None, name="serve"):
        self._predict = predict
        sizes = sorted({int(b) for b in buckets})
        if not sizes:
            raise MXNetError("empty bucket ladder")
        self._buckets = tuple(sizes)
        self._max_batch = sizes[-1]
        self._max_latency = max_latency_ms / 1e3
        self._default_deadline = (default_deadline_ms / 1e3
                                  if default_deadline_ms else None)
        self._queue = queue.Queue(maxsize=max_queue)
        self.stats = stats if stats is not None else ServingStats(name)
        self._thread = None
        self._running = False
        self._lock = _mxsan.lock("serve/batcher.py", "self._lock")
        # drain support (control plane / graceful shutdown): pause()
        # closes admission (submit sheds with a retryable Overloaded so
        # routers reroute), quiesce() waits for the queue + the in-flight
        # batch to flush, swap_predict() retargets the dispatch loop.
        self._accepting = True
        self._pause_reason = ""

    # -- lifecycle ------------------------------------------------------
    def start(self):
        with self._lock:
            if self._running:
                return self
            self._running = True
            self._thread = threading.Thread(target=self._loop,
                                            name="mxtpu-batcher",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, drain=True):
        with self._lock:
            if not self._running:
                return
            self._running = False
        self._queue.put(_STOP)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if not drain:
            self._fail_pending(MXNetError("batcher stopped"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _fail_pending(self, err):
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            self._queue.task_done()
            if req is not _STOP:
                req.future.set_exception(err)

    # -- drain hooks (rollout / graceful shutdown) ----------------------
    @property
    def accepting(self):
        return self._accepting

    def pause(self, reason="draining"):
        """Close admission: every subsequent submit() sheds with a
        retryable Overloaded naming `reason`. Queued and in-flight
        requests still complete — pause starts a drain, it does not
        cancel anything."""
        self._pause_reason = reason
        self._accepting = False

    def resume(self):
        self._accepting = True

    def quiesce(self, timeout=None):
        """Wait until the admission queue is empty AND no batch is in
        flight (pause() first, or new arrivals can starve this forever).
        Tracked through the queue's unfinished-task count — task_done is
        only called AFTER a batch's futures resolve, so there is no
        popped-but-not-yet-dispatching race window. Returns True when
        drained, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._queue.unfinished_tasks:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)
        return True

    def swap_predict(self, predict):
        """Atomically retarget the dispatch loop at a new predict
        callable (zero-downtime weight swap: the attribute store is
        atomic, and _run_group reads it once per batch — an in-flight
        batch finishes on the generation it started with)."""
        self._predict = predict

    # -- admission ------------------------------------------------------
    def submit(self, inputs, deadline_ms=None):
        """Enqueue one request; returns a Future resolving to the list of
        per-sample outputs. Raises Overloaded when the admission queue is
        full or paused for drain (retryable — the caller should back
        off / reroute)."""
        if not self._running:
            raise MXNetError("batcher not started")
        if not self._accepting:
            self.stats.incr("shed_draining")
            raise Overloaded(
                f"admission paused ({self._pause_reason or 'draining'}); "
                "retry against another replica")
        deadline = None
        if deadline_ms is not None:
            deadline = time.monotonic() + deadline_ms / 1e3
        elif self._default_deadline is not None:
            deadline = time.monotonic() + self._default_deadline
        req = _Request(inputs, deadline)
        self.stats.incr("requests_total")
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.stats.incr("shed_queue_full")
            raise Overloaded(
                f"admission queue full ({self._queue.maxsize} pending); "
                "retry later") from None
        self.stats.set_gauge("queue_depth", self._queue.qsize())
        return req.future

    def __call__(self, inputs, deadline_ms=None, timeout=None):
        """Synchronous submit().result() convenience."""
        return self.submit(inputs, deadline_ms).result(timeout=timeout)

    # -- dispatch loop --------------------------------------------------
    def _loop(self):
        while True:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                if not self._running:
                    return
                continue
            if first is _STOP:
                self._queue.task_done()
                return
            batch = [first]
            stop_after = False
            window_end = first.enqueue_t + self._max_latency
            while len(batch) < self._max_batch:
                wait = window_end - time.monotonic()
                try:
                    item = (self._queue.get_nowait() if wait <= 0
                            else self._queue.get(timeout=wait))
                except queue.Empty:
                    break
                if item is _STOP:
                    self._queue.task_done()
                    stop_after = True
                    break
                batch.append(item)
            try:
                self._dispatch(batch)
            finally:
                # quiesce() keys off unfinished_tasks: a request counts
                # until its future is RESOLVED, not merely popped
                for _ in batch:
                    self._queue.task_done()
            if stop_after:
                return

    def _bucket_for(self, n):
        for s in self._buckets:
            if s >= n:
                return s
        return self._max_batch

    def _dispatch(self, batch):
        now = time.monotonic()
        live = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                self.stats.incr("shed_deadline")
                req.future.set_exception(DeadlineExceeded(
                    "deadline expired while queued; retry with more "
                    "headroom"))
            else:
                live.append(req)
        self.stats.set_gauge("queue_depth", self._queue.qsize())
        if not live:
            self.stats.publish()
            return
        # shape-bucketing: one padded batch per sample signature
        groups = {}
        for req in live:
            sig = tuple((k, tuple(_np.shape(v)), str(_np.asarray(v).dtype))
                        for k, v in sorted(req.inputs.items()))
            groups.setdefault(sig, []).append(req)
        for reqs in groups.values():
            self._run_group(reqs)

    def _run_group(self, reqs):
        from .. import profiler
        t0 = time.monotonic()
        n = len(reqs)
        bucket = self._bucket_for(n)
        try:
            stacked = {}
            for name in reqs[0].inputs:
                rows = [_np.asarray(r.inputs[name]) for r in reqs]
                arr = _np.stack(rows, axis=0)
                if bucket > n:
                    widths = [(0, bucket - n)] + [(0, 0)] * (arr.ndim - 1)
                    arr = _np.pad(arr, widths)
                stacked[name] = arr
            # attribution: the predict call is the request's device time;
            # off (the default) this is the shared no-op span
            with profiler.span("compute", args={"bucket": bucket}):
                outs = self._predict(stacked)
                outs = [_np.asarray(o) for o in outs]
        except Exception as e:  # noqa: BLE001 — fail the requests, not the loop
            self.stats.incr("errors", n)
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            self.stats.publish()
            return
        t1 = time.monotonic()
        for i, r in enumerate(reqs):
            r.future.set_result([o[i] for o in outs])
            self.stats.latency.observe(t1 - r.enqueue_t)
            self.stats.queue_wait.observe(t0 - r.enqueue_t)
        self.stats.forward_time.observe(t1 - t0)
        self.stats.observe_bucket(
            bucket, [t0 - r.enqueue_t for r in reqs], t1 - t0)
        self.stats.incr("responses_ok", n)
        self.stats.incr("batches_total")
        self.stats.incr("padded_rows_total", bucket - n)
        self.stats.set_gauge("batch_occupancy", n / bucket)
        self.stats.publish()
        if profiler.attribution_enabled():
            # queue_wait cannot be a `with` span (enqueue happened on the
            # submit thread): book the OLDEST request's measured wait, then
            # close this dispatch as one attribution step
            profiler.observe_phase(
                "queue_wait", (t0 - reqs[0].enqueue_t) * 1e3,
                t0=reqs[0].enqueue_t, args={"bucket": bucket})
            profiler.phase_step_end()
        if profiler._state["running"]:
            profiler._record(f"{self.stats.name}::batch[{bucket}]",
                             "serving", t0 * 1e6, (t1 - t0) * 1e6)
