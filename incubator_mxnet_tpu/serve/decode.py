"""Continuous-batching autoregressive decode over a paged KV-cache.

Predict-only serving (batcher.py) batches at REQUEST granularity: a
batch runs to completion before the next one forms. Autoregressive
decode would waste most of that batch — sequences finish at different
lengths, and a request-level batch holds every slot hostage to its
longest member. This module schedules at ITERATION granularity instead
(the continuous-batching discipline of Orca/vLLM, applied here on the
Ragged-Paged-Attention TPU layout, arXiv:2604.15464): every decode step
first RETIRES finished sequences and ADMITS waiting ones into the freed
slots, so the fixed-shape decode executable stays full under load.

Three pieces:

``PageAllocator``
    Free-list allocator over a fixed pool of KV pages. Sequences own
    whole pages (``page_size`` token rows each); admit pops page ids
    off the free list, retire pushes them back — ZERO data copies in
    either direction, because the pages themselves never move: only the
    per-sequence page table (the indirection the ragged kernel reads)
    changes.

``DecodePredictor``
    Owns the decode-side executables in the two-tier compile cache:
    one PREFILL executable per prompt-length bucket (the Predictor
    ladder discipline, keys ``serve:prefill[...]``) and exactly ONE
    fixed-shape DECODE executable over the padded slot batch (key
    ``serve:decode[...]``). Idle slots ride along with position -1 and
    their KV writes dropped via out-of-bounds scatter, so steady-state
    decode does ZERO retraces regardless of which sequences come and go.

``DecodeScheduler``
    The iteration-level loop: bounded admission queue (Overloaded shed
    when full, when paused for drain/rollout, or when the projected
    queue wait breaches ``MXNET_DECODE_QUEUE_BOUND_MS`` — the PR-10
    queue-wait-histogram admission signal), per-step
    ``fault.inject("decode")`` chaos hook, and pause/resume/quiesce
    mirroring DynamicBatcher so the PR-12 control plane drains decode
    exactly like predict.

Lock hierarchy (declared in tools/mxlint/lock_order.py): scheduler
``self._lock`` is outermost and guards queue + slot tables only — never
held across device calls; predictor ``self._compile_lock`` guards
executable construction; allocator ``self._alloc_lock`` is a leaf.
The KV pool device arrays are touched ONLY by the scheduler loop
thread, so they need no lock at all.
"""
from __future__ import annotations

import math
import queue
import threading
import time
from collections import deque

import numpy as _np

from ..base import MXNetError
from .. import util
from . import reqtrace as _rt
from .batcher import DeadlineExceeded, Overloaded
from .predictor import BucketLadder
from .stats import ServingStats
from .. import mxsan as _mxsan

__all__ = ["PageAllocator", "DecodePredictor", "DecodeScheduler",
           "DecodeStream"]

_EOS = object()


class PageAllocator:
    """Refcounted free-list allocator over ``num_pages`` KV pages.

    O(1) alloc/free of page IDS only; the backing (P, page_size, H, D)
    pool arrays are owned by the scheduler and never reshaped or
    compacted. Exhaustion raises the retryable ``Overloaded`` (the
    caller either sheds 503 or leaves the request queued); freeing a
    page that is not live raises — a double free here would silently
    corrupt another sequence's context, so it must be loud.

    Pages carry a refcount for the prefix cache (serve/prefix_cache.py):
    ``alloc`` grants exclusive pages (refcount 1), ``share`` adds a
    holder to an already-live page (a cache hit costs no copy), ``free``
    drops one hold and only returns the page to the free list when the
    LAST holder lets go. ``fork`` is the copy-on-write claim: the first
    divergent WRITE to a shared page trades the caller's hold for a
    fresh exclusive page (the caller copies the rows); an exclusive page
    forks to itself, so the unshared fast path stays zero-copy.
    """

    def __init__(self, num_pages):
        if num_pages < 1:
            raise MXNetError("PageAllocator needs at least one page")
        self.num_pages = int(num_pages)
        self._alloc_lock = _mxsan.lock("serve/decode.py", "self._alloc_lock")
        # pop() takes from the tail: keep low page ids first for
        # readable tests, recency-reuse for cache locality in practice
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._live_set = set()
        self._refs = {}
        self.high_water = 0

    def alloc(self, n):
        """Pop `n` page ids; all-or-nothing (no partial grants)."""
        n = int(n)
        if n < 1:
            raise MXNetError(f"alloc({n}): need at least one page")
        with self._alloc_lock:
            if n > len(self._free):
                raise Overloaded(
                    f"KV page pool exhausted: want {n} pages, "
                    f"{len(self._free)}/{self.num_pages} free")
            pages = [self._free.pop() for _ in range(n)]
            self._live_set.update(pages)
            for p in pages:
                self._refs[p] = 1
            self.high_water = max(self.high_water, len(self._live_set))
        return pages

    def share(self, pages):
        """Add one hold per page; pages must already be live (sharing a
        dead page would alias the free list)."""
        with self._alloc_lock:
            for p in pages:
                if p not in self._live_set:
                    raise MXNetError(f"share of non-live KV page {p}")
            for p in pages:
                self._refs[p] += 1
        return pages

    def fork(self, page):
        """Copy-on-write claim before the first divergent write to
        ``page``. Returns ``(page_to_write, copied)``: the same page
        with ``copied=False`` when the caller is the only holder, else
        a fresh exclusive page (caller's hold on the original released)
        with ``copied=True`` — the CALLER copies the row data, this
        class only moves ids. May raise Overloaded when no free page
        remains to back the copy."""
        with self._alloc_lock:
            if page not in self._live_set:
                raise MXNetError(f"fork of non-live KV page {page}")
            if self._refs[page] == 1:
                return page, False
            if not self._free:
                raise Overloaded(
                    f"KV page pool exhausted: no free page to fork "
                    f"shared page {page}")
            fresh = self._free.pop()
            self._live_set.add(fresh)
            self._refs[fresh] = 1
            self._refs[page] -= 1
            self.high_water = max(self.high_water, len(self._live_set))
        return fresh, True

    def free(self, pages):
        with self._alloc_lock:
            for p in pages:
                if p not in self._live_set:
                    raise MXNetError(f"double free of KV page {p}")
            for p in pages:
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    self._live_set.remove(p)
                    self._free.append(p)

    def refcount(self, page):
        """Current holder count (0 for a free page)."""
        with self._alloc_lock:
            return self._refs.get(page, 0)

    @property
    def live(self):
        with self._alloc_lock:
            return len(self._live_set)

    @property
    def free_count(self):
        with self._alloc_lock:
            return len(self._free)

    @property
    def used_count(self):
        with self._alloc_lock:
            return len(self._live_set)

    @property
    def shared_count(self):
        """Pages held by more than one owner (the prefix-cache overlap
        the mxnet_kv_pages_shared gauge reports)."""
        with self._alloc_lock:
            return sum(1 for rc in self._refs.values() if rc >= 2)


class DecodePredictor:
    """Decode-side executables for a single-layer attention LM.

    params (all float32 numpy/jax arrays):
      emb (V, E) | wq, wk, wv, wo (E, E) | w_out (E, V), with
      E = num_heads * head_dim. One pre-norm-free attention block plus
      a residual and an output projection — deliberately small, but it
      exercises every serving-side mechanism (paged KV scatter, ragged
      attention reads, greedy sampling) the full model would.

    Geometry (page_size/num_pages/max_pages_per_seq/slots) lives here
    because the DECODE EXECUTABLE'S SHAPE bakes it in: changing any of
    it is a recompile, so it is constructor state, not a runtime knob.
    Prompts are padded up a `prompt_buckets` ladder exactly like
    Predictor; generation is greedy argmax, which makes every stream's
    token sequence a pure function of its prompt — the property the
    continuous-vs-sequential bit-identity test relies on.
    """

    def __init__(self, params, *, num_heads, head_dim, vocab,
                 prompt_buckets=(4, 8, 16), page_size=None, num_pages=None,
                 max_pages_per_seq=None, slots=None):
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.vocab = int(vocab)
        self.embed = self.num_heads * self.head_dim
        self.page_size = int(page_size if page_size is not None
                             else util.getenv_int("MXNET_KV_PAGE_SIZE"))
        self.num_pages = int(num_pages if num_pages is not None
                             else util.getenv_int("MXNET_KV_PAGES"))
        self.max_pages_per_seq = int(
            max_pages_per_seq if max_pages_per_seq is not None
            else util.getenv_int("MXNET_KV_PAGES_PER_SEQ"))
        self.slots = int(slots if slots is not None
                         else util.getenv_int("MXNET_DECODE_SLOTS"))
        if self.page_size < 1 or self.num_pages < 1 or self.slots < 1:
            raise MXNetError("decode geometry must be positive")
        if self.max_pages_per_seq > self.num_pages:
            raise MXNetError("MXNET_KV_PAGES_PER_SEQ exceeds MXNET_KV_PAGES")
        self.ladder = BucketLadder(prompt_buckets)
        exp = {"emb": (self.vocab, self.embed),
               "wq": (self.embed, self.embed),
               "wk": (self.embed, self.embed),
               "wv": (self.embed, self.embed),
               "wo": (self.embed, self.embed),
               "w_out": (self.embed, self.vocab)}
        for name, shape in exp.items():
            if name not in params:
                raise MXNetError(f"param {name} missing (need {sorted(exp)})")
            got = tuple(params[name].shape)
            if got != shape:
                raise MXNetError(f"param {name}: shape {got} != {shape}")
        import jax.numpy as jnp
        self._param_vals = {k: jnp.asarray(v, jnp.float32)
                            for k, v in params.items()}
        self._compile_lock = _mxsan.lock(
            "serve/decode.py", "self._compile_lock")
        self._prefill_fns = {}
        self._decode_fn = None
        self._warm_keys = set()

    @classmethod
    def toy(cls, seed=0, *, vocab=32, num_heads=2, head_dim=8, **kw):
        """Deterministically-initialized small model (tests/bench)."""
        rng = _np.random.RandomState(seed)
        e = num_heads * head_dim

        def w(*shape, s=0.3):
            return (rng.standard_normal(shape) * s).astype(_np.float32)

        params = {"emb": w(vocab, e, s=0.5), "wq": w(e, e), "wk": w(e, e),
                  "wv": w(e, e), "wo": w(e, e), "w_out": w(e, vocab)}
        return cls(params, num_heads=num_heads, head_dim=head_dim,
                   vocab=vocab, **kw)

    # -- geometry helpers ----------------------------------------------
    def pages_for(self, prompt_len, max_new_tokens):
        """Pages a stream owns for its whole life (allocated up front at
        admission — continuous batching never reallocates mid-flight)."""
        return max(1, math.ceil((prompt_len + max_new_tokens)
                                / self.page_size))

    # -- traced model fns ----------------------------------------------
    def _make_prefill(self, t_bucket):
        h_, d_, ps, p_ = (self.num_heads, self.head_dim, self.page_size,
                          self.num_pages)
        e_ = self.embed
        scale = 1.0 / math.sqrt(d_)

        def call(params, tokens, n, k_pages, v_pages, ptrow):
            # tokens (1, T) int32; n () int32 TRACED (real prompt len —
            # one executable per bucket, not per length); ptrow
            # (max_pages_per_seq,) int32 page ids for this sequence
            import jax
            import jax.numpy as jnp
            t = t_bucket
            h = params["emb"][tokens[0]]                     # (T, E)
            q = (h @ params["wq"]).reshape(t, h_, d_)
            k = (h @ params["wk"]).reshape(t, h_, d_)
            v = (h @ params["wv"]).reshape(t, h_, d_)
            s = jnp.einsum("qhd,khd->hqk", q * scale, k)
            pos = jnp.arange(t, dtype=jnp.int32)
            mask = (pos[:, None] >= pos[None, :]) & (pos[None, :] < n)
            s = jnp.where(mask[None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            a = jnp.einsum("hqk,khd->qhd", p, v).reshape(t, e_)
            o = a @ params["wo"] + h
            logits = o @ params["w_out"]                     # (T, V)
            nxt = jnp.argmax(logits[n - 1], axis=-1).astype(jnp.int32)
            # scatter the prompt's KV rows into the owned pages; padded
            # rows (pos >= n) aim past the pool and mode="drop" discards
            flat = ptrow[pos // ps] * ps + pos % ps
            flat = jnp.where(pos < n, flat, p_ * ps)
            kp = k_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                k, mode="drop").reshape(p_, ps, h_, d_)
            vp = v_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                v, mode="drop").reshape(p_, ps, h_, d_)
            return nxt, kp, vp

        return call

    def _make_decode(self):
        h_, d_, ps, p_, s_ = (self.num_heads, self.head_dim, self.page_size,
                              self.num_pages, self.slots)
        e_ = self.embed

        def call(params, tokens, positions, k_pages, v_pages, page_tables):
            # tokens (S,) int32 — last emitted token per slot;
            # positions (S,) int32 — its KV write position, -1 = idle
            # slot (writes dropped, attention reads page 0 harmlessly
            # and the output row is ignored by the scheduler)
            import jax.numpy as jnp
            from ..parallel.paged_attention import paged_attention
            active = positions >= 0
            pos = jnp.maximum(positions, 0)
            h = params["emb"][tokens]                        # (S, E)
            q = (h @ params["wq"]).reshape(s_, h_, d_)
            k = (h @ params["wk"]).reshape(s_, h_, d_)
            v = (h @ params["wv"]).reshape(s_, h_, d_)
            row = jnp.arange(s_, dtype=jnp.int32)
            flat = page_tables[row, pos // ps] * ps + pos % ps
            flat = jnp.where(active, flat, p_ * ps)
            kp = k_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                k, mode="drop").reshape(p_, ps, h_, d_)
            vp = v_pages.reshape(p_ * ps, h_, d_).at[flat].set(
                v, mode="drop").reshape(p_, ps, h_, d_)
            attn = paged_attention(q, kp, vp, page_tables, pos + 1)
            o = attn.reshape(s_, e_) @ params["wo"] + h
            logits = o @ params["w_out"]                     # (S, V)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, kp, vp

        return call

    # -- executables ----------------------------------------------------
    def _geom_tag(self):
        return (f"p{self.num_pages}x{self.page_size},h{self.num_heads}"
                f"x{self.head_dim},v{self.vocab}")

    def _prefill_key(self, t_bucket):
        return f"serve:prefill[t{t_bucket},{self._geom_tag()}]"

    def _decode_key(self):
        return f"serve:decode[s{self.slots},{self._geom_tag()}]"

    def _exec_prefill(self, t_bucket):
        with self._compile_lock:
            fn = self._prefill_fns.get(t_bucket)
            if fn is None:
                from .. import compile_cache as _cc
                fn = _cc.cached_jit(self._prefill_key(t_bucket),
                                    self._make_prefill(t_bucket))
                self._prefill_fns[t_bucket] = fn
        return fn

    def _exec_decode(self):
        with self._compile_lock:
            if self._decode_fn is None:
                from .. import compile_cache as _cc
                self._decode_fn = _cc.cached_jit(self._decode_key(),
                                                 self._make_decode())
        return self._decode_fn

    def kv_pool(self):
        """Fresh zeroed (P, page_size, H, D) key and value pools."""
        import jax.numpy as jnp
        shape = (self.num_pages, self.page_size, self.num_heads,
                 self.head_dim)
        return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)

    def warmup(self):
        """AOT-compile every prefill bucket and THE decode executable.

        Returns {"prefill:<bucket>": kind, ..., "decode": kind} with
        kind in {"hit", "disk", "miss"} (compile_cache.warmup): a warm
        boot against a populated MXNET_EXEC_CACHE_DIR reports no
        "miss" anywhere, i.e. zero retraces before the first request.
        """
        import jax
        import jax.numpy as jnp
        i32 = jnp.int32
        kv = jax.ShapeDtypeStruct((self.num_pages, self.page_size,
                                   self.num_heads, self.head_dim),
                                  jnp.float32)
        ptrow = jax.ShapeDtypeStruct((self.max_pages_per_seq,), i32)
        out = {}
        for t_bucket in self.ladder.sizes:
            fn = self._exec_prefill(t_bucket)
            out[f"prefill:{t_bucket}"] = fn.warmup(
                self._param_vals,
                jax.ShapeDtypeStruct((1, t_bucket), i32),
                jax.ShapeDtypeStruct((), i32), kv, kv, ptrow)
            self._warm_keys.add(f"prefill:{t_bucket}")
        fn = self._exec_decode()
        out["decode"] = fn.warmup(
            self._param_vals,
            jax.ShapeDtypeStruct((self.slots,), i32),
            jax.ShapeDtypeStruct((self.slots,), i32), kv, kv,
            jax.ShapeDtypeStruct((self.slots, self.max_pages_per_seq), i32))
        self._warm_keys.add("decode")
        return out

    @property
    def is_warm(self):
        want = {f"prefill:{b}" for b in self.ladder.sizes} | {"decode"}
        return want <= self._warm_keys

    # -- runtime entry points (called by the scheduler loop) ------------
    def prefill(self, prompt, k_pages, v_pages, ptrow):
        """Run one prompt; returns (first generated token id, updated
        pools). Raises MXNetError when the prompt exceeds the ladder."""
        import jax.numpy as jnp
        n = len(prompt)
        t_bucket = self.ladder.bucket_for(n)
        if t_bucket is None:
            raise MXNetError(f"prompt length {n} exceeds the prefill "
                             f"ladder {self.ladder.sizes}")
        toks = _np.zeros((1, t_bucket), _np.int32)
        toks[0, :n] = prompt
        fn = self._exec_prefill(t_bucket)
        nxt, kp, vp = fn(self._param_vals, jnp.asarray(toks),
                         jnp.asarray(n, jnp.int32), k_pages, v_pages,
                         jnp.asarray(ptrow, jnp.int32))
        self._warm_keys.add(f"prefill:{t_bucket}")
        return int(nxt), kp, vp

    def decode(self, tokens, positions, k_pages, v_pages, page_tables):
        """One batched decode step over all slots (idle rows pos=-1)."""
        import jax.numpy as jnp
        fn = self._exec_decode()
        nxt, kp, vp = fn(self._param_vals,
                         jnp.asarray(tokens, jnp.int32),
                         jnp.asarray(positions, jnp.int32),
                         k_pages, v_pages,
                         jnp.asarray(page_tables, jnp.int32))
        self._warm_keys.add("decode")
        return _np.asarray(nxt), kp, vp


class DecodeStream:
    """Handle for one in-flight generation: iterate for tokens as they
    land (per-token streaming), or block on result() for the full list.
    The first token arrives from PREFILL (its latency is the TTFT);
    every later token from a decode step."""

    def __init__(self, prompt, max_new_tokens, eos_id, deadline):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.deadline = deadline
        self.submit_t = time.monotonic()
        self.ttft_ms = None
        self._q = queue.Queue()
        self._tokens = []
        self._done = threading.Event()
        self._error = None
        self._cancelled = False
        # scheduler-owned bookkeeping
        self._slot = -1
        self._pages = None
        self._pages_needed = 0
        self._last_t = None
        self._kv_import = None
        # request tracing (serve/reqtrace.py): the router-minted context
        # and the scheduler-measured TTFT budget components
        self._trace = None
        self._budget = None
        # speculative-decode state (spec schedulers only)
        self._draft = None
        self._spec_k = 0
        self._spec_ema = None

    def _deliver(self, tok, now):
        if self.ttft_ms is None:
            self.ttft_ms = (now - self.submit_t) * 1e3
        self._tokens.append(tok)
        self._last_t = now
        self._q.put(tok)

    def _finish(self, error=None):
        self._error = error
        self._done.set()
        self._q.put(_EOS)

    def cancel(self):
        """Ask the scheduler to retire this stream at its next step
        (client went away); already-queued tokens stay readable."""
        self._cancelled = True

    @property
    def done(self):
        return self._done.is_set()

    @property
    def error(self):
        return self._error

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _EOS:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise DeadlineExceeded("stream still running")
        if self._error is not None:
            raise self._error
        return list(self._tokens)


class DecodeScheduler:
    """Iteration-level scheduler: one loop thread interleaves
    retire -> admit -> step so freed slots and freed KV pages are reused
    on the very next iteration (see module docstring)."""

    def __init__(self, predictor, *, stats=None, max_queue=None,
                 max_new_tokens=None, queue_bound_ms=None, name="decode",
                 prefix_cache=None, chunk_prefill=None, spec_decode=None,
                 spec_k=None):
        self.predictor = predictor
        # speculative decoding (serve/spec_decode.py): when enabled the
        # loop's step is draft-propose + ONE batched verify instead of
        # ONE decode dispatch; emitted tokens are bit-identical under
        # greedy, so this is purely a throughput knob
        if spec_decode is None:
            spec_decode = util.getenv_bool("MXNET_SPEC_DECODE")
        self.spec = None
        if spec_decode:
            from .spec_decode import SpecDecoder
            self.spec = SpecDecoder(predictor, k=spec_k)
        self.stats = stats if stats is not None else ServingStats(name)
        self._max_queue = int(max_queue if max_queue is not None
                              else util.getenv_int("MXNET_DECODE_QUEUE"))
        self._default_max_new = int(
            max_new_tokens if max_new_tokens is not None
            else util.getenv_int("MXNET_DECODE_MAX_NEW_TOKENS"))
        self._queue_bound_ms = float(
            queue_bound_ms if queue_bound_ms is not None
            else util.getenv_int("MXNET_DECODE_QUEUE_BOUND_MS"))
        self.allocator = PageAllocator(predictor.num_pages)
        # prefix_cache: True builds a PrefixCache over this scheduler's
        # allocator; or pass an instance already bound to it. Cache hits
        # are completed by CHUNKED suffix prefill (serve/disagg.py), so
        # a chunk executable is built lazily unless chunk_prefill hands
        # in a pre-warmed PrefillPredictor.
        if prefix_cache is True:
            from .prefix_cache import PrefixCache
            prefix_cache = PrefixCache(self.allocator, predictor.page_size)
        self.prefix_cache = prefix_cache
        self._chunk_fn = chunk_prefill
        s = predictor.slots
        self._lock = _mxsan.lock("serve/decode.py", "self._lock")
        self._wake = threading.Event()
        self._waiting = deque()
        self._active = [None] * s
        self._positions = _np.full(s, -1, _np.int32)
        self._tokens = _np.zeros(s, _np.int32)
        self._page_tables = _np.zeros((s, predictor.max_pages_per_seq),
                                      _np.int32)
        self._k_pages = None
        self._v_pages = None
        self._running = False
        self._accepting = True
        self._pause_reason = ""
        self._thread = None
        self.stats.set_gauge("kv_pages_total", predictor.num_pages)

    # -- lifecycle ------------------------------------------------------
    def start(self):
        with self._lock:
            if self._running:
                return self
            self._running = True
        if self._k_pages is None:
            self._k_pages, self._v_pages = self.predictor.kv_pool()
        # AOT-build the fixed-shape batched-verify executable before the
        # loop thread serves traffic, so speculation never retraces
        # mid-stream (same contract as DecodePredictor.warmup: a warm
        # boot against a populated cache dir reports "disk", not "miss").
        if self.spec is not None and not self.spec.is_warm:
            self.spec.warmup()
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-decode", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True):
        if drain and self._thread is not None:
            self.pause("stop")
            self.quiesce(timeout=30)
        with self._lock:
            if not self._running:
                return
            self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self._fail_all(MXNetError("decode scheduler stopped"))

    def _fail_all(self, err):
        with self._lock:
            victims = list(self._waiting) + [st for st in self._active
                                             if st is not None]
            self._waiting.clear()
            self._active = [None] * self.predictor.slots
            self._positions[:] = -1
        for st in victims:
            if st._pages:
                self.allocator.free(st._pages)
                st._pages = None
            st._finish(err)
        self._set_pool_gauges()

    # -- admission control (control-plane surface) ----------------------
    def pause(self, reason="pause"):
        with self._lock:
            self._accepting = False
            self._pause_reason = reason

    def resume(self):
        with self._lock:
            self._accepting = True
            self._pause_reason = ""

    @property
    def accepting(self):
        with self._lock:
            return self._accepting

    @property
    def active_streams(self):
        """Streams queued or occupying a slot — the load-report signal
        routers use for decode placement."""
        with self._lock:
            return (len(self._waiting)
                    + sum(1 for st in self._active if st is not None))

    def quiesce(self, timeout=30.0):
        """Wait until no stream is queued or in a slot. Pair with
        pause(): quiescing with admission open may never converge."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                idle = (not self._waiting
                        and all(st is None for st in self._active))
            if idle:
                return True
            self._wake.set()
            time.sleep(0.005)
        return False

    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_ms=None, kv_import=None, trace=None):
        """Queue one generation; returns a DecodeStream immediately.

        Sheds (Overloaded, 503-retryable) rather than queueing into
        collapse: when paused, when the bounded queue is full, and when
        the PROJECTED queue wait — p95 of recent admission waits scaled
        by the queue depth ahead of this request — breaches
        MXNET_DECODE_QUEUE_BOUND_MS (0 disables). Oversized requests
        (prompt beyond the ladder, page demand beyond the per-sequence
        cap) raise plain MXNetError: retrying those elsewhere cannot
        succeed, so they must not be labelled retryable.

        ``kv_import`` is the disaggregated admission path: a dict with
        ``k_rows``/``v_rows`` ((m, page_size, H, D) float32 rows as
        exported by a prefill replica), ``n`` (prompt length those rows
        cover) and ``next_token`` (the prefill's greedy pick). Admission
        then writes the shipped rows into freshly allocated pages and
        starts decoding at position ``n`` — no local prefill, no
        ladder constraint on the prompt.

        ``trace`` (a reqtrace.RequestTrace, or None) rides the stream so
        admission books ``decode_admission``/``first_step`` spans and
        the TTFT budget components against the request's trace id.
        """
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("empty prompt")
        if not self._running:
            raise MXNetError("decode scheduler not started")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self._default_max_new)
        if max_new < 1:
            raise MXNetError(f"max_new_tokens={max_new}: need >= 1")
        if kv_import is not None:
            kv_import = self._check_kv_import(kv_import, prompt)
        elif self.predictor.ladder.bucket_for(len(prompt)) is None:
            raise MXNetError(
                f"prompt length {len(prompt)} exceeds the prefill "
                f"ladder {self.predictor.ladder.sizes}")
        pages_needed = self.predictor.pages_for(len(prompt), max_new)
        if pages_needed > self.predictor.max_pages_per_seq:
            raise MXNetError(
                f"request needs {pages_needed} KV pages, per-sequence cap "
                f"is {self.predictor.max_pages_per_seq} "
                f"(MXNET_KV_PAGES_PER_SEQ)")
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms else None)
        with self._lock:
            if not self._accepting:
                self.stats.incr("shed_draining")
                raise Overloaded(
                    f"decode admission paused: {self._pause_reason}")
            if len(self._waiting) >= self._max_queue:
                self.stats.incr("shed_queue_full")
                raise Overloaded(
                    f"decode queue full ({self._max_queue})")
            self._shed_if_projected_wait_locked()
            st = DecodeStream(prompt, max_new, eos_id, deadline)
            st._pages_needed = pages_needed
            st._kv_import = kv_import
            st._trace = trace
            self._waiting.append(st)
            self.stats.incr("requests_total")
            self.stats.incr("decode_streams_total")
            self.stats.set_gauge("queue_depth", len(self._waiting))
        self._wake.set()
        return st

    def _shed_if_projected_wait_locked(self):
        if self._queue_bound_ms <= 0:
            return
        qw = self.stats.queue_wait
        if qw.count < 8:
            return  # no signal yet: admit optimistically
        projected_ms = qw.percentile(95) * 1e3 * (len(self._waiting) + 1)
        if projected_ms > self._queue_bound_ms:
            self.stats.incr("shed_projected")
            raise Overloaded(
                f"projected queue wait {projected_ms:.1f} ms breaches "
                f"MXNET_DECODE_QUEUE_BOUND_MS={self._queue_bound_ms:.0f}")

    def _check_kv_import(self, kv_import, prompt):
        p = self.predictor
        try:
            n = int(kv_import["n"])
            nxt = int(kv_import["next_token"])
            k_rows = _np.asarray(kv_import["k_rows"], _np.float32)
            v_rows = _np.asarray(kv_import["v_rows"], _np.float32)
        except (KeyError, TypeError, ValueError) as e:
            raise MXNetError(f"malformed kv_import: {e}")
        if n != len(prompt):
            raise MXNetError(f"kv_import covers {n} tokens but the "
                             f"prompt has {len(prompt)}")
        m = math.ceil(n / p.page_size)
        row_shape = (m, p.page_size, p.num_heads, p.head_dim)
        for name, rows in (("k_rows", k_rows), ("v_rows", v_rows)):
            if tuple(rows.shape) != row_shape:
                raise MXNetError(
                    f"kv_import {name} shape {tuple(rows.shape)} != "
                    f"{row_shape} for this replica's geometry")
        return {"n": n, "next_token": nxt,
                "k_rows": k_rows, "v_rows": v_rows}

    # -- the loop -------------------------------------------------------
    def _loop(self):
        while True:
            with self._lock:
                if not self._running:
                    return
                busy = (bool(self._waiting)
                        or any(st is not None for st in self._active))
            if not busy:
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            try:
                self._admit()
                self._step()
            except Exception as e:  # noqa: BLE001 — loop must survive
                self.stats.incr("errors")
                self._fail_all(e if isinstance(e, MXNetError)
                               else MXNetError(f"decode step failed: {e}"))
            self.stats.publish()

    def _set_pool_gauges(self):
        live = self.allocator.live
        self.stats.set_gauge("kv_pages_live", live)
        self.stats.set_gauge("kv_page_occupancy",
                             live / self.allocator.num_pages)
        self.stats.set_gauge("kv_pages_free", self.allocator.free_count)
        self.stats.set_gauge("kv_pages_used", live)
        self.stats.set_gauge("kv_pages_shared", self.allocator.shared_count)
        if self.prefix_cache is not None:
            pc = self.prefix_cache.stats()
            self.stats.set_gauge("prefix_cache_hits", pc["hits"])
            self.stats.set_gauge("prefix_cache_misses", pc["misses"])
            self.stats.set_gauge("prefix_tokens_saved", pc["tokens_saved"])
        with self._lock:
            n_active = sum(st is not None for st in self._active)
            depth = len(self._waiting)
        self.stats.set_gauge("decode_active", n_active)
        self.stats.set_gauge("queue_depth", depth)

    def _chunker(self):
        if self._chunk_fn is None:
            from .disagg import PrefillPredictor
            self._chunk_fn = PrefillPredictor(self.predictor)
        return self._chunk_fn

    def _claim_pages_locked(self, st):
        """Build the admission plan for one stream while holding the
        scheduler lock: every page the stream will EVER touch is claimed
        here (exclusive alloc, shared prefix-cache hit, or CoW fork of
        a shared tail), all-or-nothing. Raises Overloaded to hold the
        queue with nothing leaked."""
        if st._kv_import is not None:
            return {"mode": "import",
                    "pages": self.allocator.alloc(st._pages_needed)}
        if self.prefix_cache is None:
            return {"mode": "plain",
                    "pages": self.allocator.alloc(st._pages_needed)}
        pages, covered, partial = self.prefix_cache.lookup(st.prompt)
        cow = None
        try:
            if partial:
                # the suffix prefill writes into the tail page: first
                # divergent write, so take the copy-on-write claim now
                fresh, copied = self.allocator.fork(pages[-1])
                if copied:
                    cow = (pages[-1], fresh)
                pages = pages[:-1] + [fresh]
            extra = st._pages_needed - len(pages)
            if extra > 0:
                pages = pages + self.allocator.alloc(extra)
        except Overloaded:
            if pages:
                self.allocator.free(pages)
            raise
        return {"mode": "cached", "pages": pages, "covered": covered,
                "cow": cow}

    def _admit(self):
        """Move waiting streams into free slots until slots or pages run
        out. Pages are claimed for the stream's WHOLE lifetime up front
        — admission is the only place a stream can block on memory, so
        an admitted stream always runs to completion."""
        while True:
            with self._lock:
                if not self._waiting:
                    return
                free_slots = [i for i, st in enumerate(self._active)
                              if st is None]
                if not free_slots:
                    return
                st = self._waiting[0]
                now = time.monotonic()
                if st.deadline is not None and now > st.deadline:
                    self._waiting.popleft()
                    self.stats.incr("shed_deadline")
                    st._finish(DeadlineExceeded(
                        "deadline expired while queued"))
                    continue
                try:
                    plan = self._claim_pages_locked(st)
                except Overloaded:
                    return  # pool exhausted: hold the queue, a retire
                    # will free pages and the next iteration re-admits
                self._waiting.popleft()
                slot = free_slots[0]
                st._slot = slot
                st._pages = plan["pages"]
                queue_wait = now - st.submit_t
            pages = plan["pages"]
            ptrow = _np.zeros(self.predictor.max_pages_per_seq, _np.int32)
            ptrow[:len(pages)] = pages
            if self.spec is not None:
                # seed the stream's draft with the prompt's KV — works
                # uniformly for plain/cached/import admission because
                # the prompt tokens are always known host-side
                st._draft = self.spec.make_draft(st.prompt)
                st._spec_k = self.spec.k
                st._spec_ema = None
            t0 = time.monotonic()
            nxt, pos = self._run_admission(st, plan, ptrow)
            now = time.monotonic()
            self.stats.queue_wait.observe(queue_wait)
            self.stats.prefill_time.observe(now - t0)
            with self._lock:
                self._page_tables[slot] = ptrow
                self._positions[slot] = pos
                self._tokens[slot] = nxt
                self._active[slot] = st
            st._deliver(nxt, now)
            self.stats.ttft.observe(
                now - st.submit_t,
                trace=st._trace.trace_id if st._trace is not None
                and st._trace.sampled else None)
            if st._trace is not None:
                # scheduler-side TTFT budget: queue wait + admission
                # device work + the residual (bookkeeping, draft seeding,
                # delivery) as first_step; the server's done row merges
                # these with the router-side legs
                ttft_ms = (now - st.submit_t) * 1e3
                queue_ms = queue_wait * 1e3
                admission_ms = (now - t0) * 1e3
                first_step_ms = max(0.0, ttft_ms - queue_ms - admission_ms)
                st._budget = {"queue_ms": round(queue_ms, 3),
                              "admission_ms": round(admission_ms, 3),
                              "first_step_ms": round(first_step_ms, 3)}
                _rt.observe(st._trace, "decode_admission", admission_ms,
                            args={"mode": plan["mode"],
                                  "pages": len(plan["pages"])})
                _rt.observe(st._trace, "first_step", first_step_ms)
            self.stats.incr("decode_tokens_total")
            if (len(st._tokens) >= st.max_new_tokens
                    or nxt == st.eos_id or st._cancelled):
                self._retire(st)
            self._set_pool_gauges()

    def _run_admission(self, st, plan, ptrow):
        """Fill the stream's pages (no scheduler lock held — device
        work). Returns (first token, decode start position)."""
        import jax.numpy as jnp
        if plan["mode"] == "import":
            imp = st._kv_import
            m = len(imp["k_rows"])
            idx = jnp.asarray(plan["pages"][:m])
            self._k_pages = self._k_pages.at[idx].set(
                jnp.asarray(imp["k_rows"]))
            self._v_pages = self._v_pages.at[idx].set(
                jnp.asarray(imp["v_rows"]))
            self.stats.incr("kv_pages_imported_total", m)
            return imp["next_token"], imp["n"]
        if plan["mode"] == "cached":
            if plan["cow"] is not None:
                src, dst = plan["cow"]
                self._k_pages = self._k_pages.at[dst].set(
                    self._k_pages[src])
                self._v_pages = self._v_pages.at[dst].set(
                    self._v_pages[src])
            nxt = self._chunked_prefill(st.prompt, plan["covered"], ptrow)
            self.prefix_cache.insert(st.prompt, list(plan["pages"]),
                                     len(st.prompt))
            return nxt, len(st.prompt)
        nxt, kp, vp = self.predictor.prefill(
            st.prompt, self._k_pages, self._v_pages, ptrow)
        self._k_pages, self._v_pages = kp, vp
        return nxt, len(st.prompt)

    def _chunked_prefill(self, prompt, start, ptrow):
        """Prefill positions start..len(prompt)-1 in fixed chunks,
        interleaving one decode step between chunks whenever slots are
        active — a colocated replica's in-flight streams never wait for
        a whole long prompt."""
        chunker = self._chunker()
        nxt = None
        for lo in range(start, len(prompt), chunker.chunk):
            if lo > start:
                with self._lock:
                    busy = any(s is not None for s in self._active)
                if busy:
                    self._step()
            nxt, kp, vp = chunker.prefill_chunk(
                prompt, lo, self._k_pages, self._v_pages, ptrow)
            self._k_pages, self._v_pages = kp, vp
        return nxt

    def _step(self):
        """One iteration's device work: the speculative draft+verify
        step when spec decode is on, the plain decode dispatch
        otherwise."""
        if self.spec is not None:
            return self._spec_step()
        return self._plain_step()

    def _plain_step(self):
        """One fixed-shape decode dispatch over all slots, then per-slot
        deliver/retire. The chaos hook fires BEFORE the device call so a
        kill lands mid-stream with tokens already flushed to clients."""
        from .. import fault
        with self._lock:
            active = [(i, st) for i, st in enumerate(self._active)
                      if st is not None]
            if not active:
                return
            tokens = self._tokens.copy()
            positions = self._positions.copy()
            page_tables = self._page_tables.copy()
        if _rt.enabled():
            # fault-site breadcrumb carries the active request trace ids
            # so a kill -9 postmortem joins the request trace
            traces = [st._trace.trace_id for _, st in active
                      if st._trace is not None]
            if traces:
                fault.flight_record("decode_step", traces=traces)
        fault.inject("decode")
        t0 = time.monotonic()
        nxt, kp, vp = self.predictor.decode(
            tokens, positions, self._k_pages, self._v_pages, page_tables)
        self._k_pages, self._v_pages = kp, vp
        now = time.monotonic()
        step_s = now - t0
        self.stats.decode_step_time.observe(step_s)
        # PR-10 queue-wait-vs-device signal, bucket = the slot batch
        self.stats.observe_bucket(self.predictor.slots, (), step_s)
        self.stats.incr("batches_total")
        self.stats.set_gauge("batch_occupancy",
                             len(active) / self.predictor.slots)
        for i, st in active:
            tok = int(nxt[i])
            with self._lock:
                self._positions[i] += 1
                self._tokens[i] = tok
            if st.deadline is not None and now > st.deadline:
                self.stats.incr("shed_deadline")
                self._retire(st, DeadlineExceeded(
                    "deadline expired mid-generation"))
                continue
            if st._cancelled:
                self._retire(st)
                continue
            if st._last_t is not None:
                self.stats.token_latency.observe(now - st._last_t)
            st._deliver(tok, now)
            self.stats.incr("decode_tokens_total")
            if (len(st._tokens) >= st.max_new_tokens
                    or tok == st.eos_id):
                self._retire(st)
        self._set_pool_gauges()

    def _spec_step(self):
        """One speculative iteration: host-side draft proposals for
        every active slot, then ONE fixed-shape batched verify dispatch,
        then longest-agreeing-prefix acceptance (see spec_decode.py for
        the rule and why greedy outputs stay bit-identical).

        Per-slot depth ``k_s`` is clamped to (a) the stream's adaptive
        k, (b) ``remaining - 1`` so the m+1 emitted tokens can never
        overshoot max_new_tokens, and (c) the stream's OWNED page
        capacity so a speculative write can never land outside pages
        claimed at admission (ptrow's zero padding would silently alias
        page 0 otherwise). Unused verify rows pad at position -1. The
        chaos hook fires BEFORE the verify dispatch, mirroring
        _plain_step's decode site."""
        from .. import fault
        spec = self.spec
        ps = self.predictor.page_size
        with self._lock:
            active = [(i, st) for i, st in enumerate(self._active)
                      if st is not None]
            if not active:
                return
            base_tokens = self._tokens.copy()
            base_positions = self._positions.copy()
            page_tables = self._page_tables.copy()
        tokens = _np.zeros((self.predictor.slots, spec.width), _np.int32)
        positions = _np.full((self.predictor.slots, spec.width), -1,
                             _np.int32)
        drafts = {}
        t_draft = time.monotonic()
        for i, st in active:
            t0 = int(base_tokens[i])
            p0 = int(base_positions[i])
            remaining = st.max_new_tokens - len(st._tokens)
            owned_cap = len(st._pages) * ps - 1 - p0
            k_s = max(0, min(st._spec_k, remaining - 1, owned_cap))
            d = st._draft.propose(t0, k_s) if k_s > 0 else []
            drafts[i] = d
            tokens[i, 0] = t0
            positions[i, 0] = p0
            for j, dt in enumerate(d):
                tokens[i, j + 1] = dt
                positions[i, j + 1] = p0 + j + 1
        self.stats.spec_draft_time.observe(time.monotonic() - t_draft)
        rt_ctxs = ()
        if _rt.enabled():
            rt_ctxs = [st._trace for _, st in active
                       if st._trace is not None]
            if rt_ctxs:
                # fault-site breadcrumb: the verify kill drill's
                # postmortem joins the request trace by these ids
                fault.flight_record(
                    "spec_verify",
                    traces=[c.trace_id for c in rt_ctxs])
        fault.inject("verify")
        t0v = time.monotonic()
        y, kp, vp = spec.verify(tokens, positions, self._k_pages,
                                self._v_pages, page_tables,
                                traces=rt_ctxs)
        self._k_pages, self._v_pages = kp, vp
        now = time.monotonic()
        step_s = now - t0v
        self.stats.spec_verify_time.observe(step_s)
        self.stats.decode_step_time.observe(step_s)
        self.stats.observe_bucket(self.predictor.slots, (), step_s)
        self.stats.incr("batches_total")
        self.stats.incr("spec_steps_total")
        self.stats.set_gauge("batch_occupancy",
                             len(active) / self.predictor.slots)
        k_live = []
        for i, st in active:
            d = drafts[i]
            k_s = len(d)
            m = 0
            while m < k_s and d[m] == int(y[i, m]):
                m += 1
            emitted = list(d[:m]) + [int(y[i, m])]
            if k_s:
                frac = m / k_s
                self.stats.spec_accept_rate.observe(frac)
                self.stats.incr("spec_tokens_proposed_total", k_s)
                self.stats.incr("spec_tokens_accepted_total", m)
                st._spec_ema = (frac if st._spec_ema is None
                                else (0.5 * frac + 0.5 * st._spec_ema))
                st._spec_k = spec.next_k(st._spec_k, st._spec_ema)
            k_live.append(st._spec_k)
            p0 = int(base_positions[i])
            # rejection rollback: truncate the draft history to the
            # accepted prefix (committed KV positions p0..p0+m); page
            # ownership is untouched — speculation never claims pages
            st._draft.sync(p0, [int(tokens[i, 0])] + list(d[:m]))
            with self._lock:
                self._positions[i] = p0 + m + 1
                self._tokens[i] = emitted[-1]
            if st.deadline is not None and now > st.deadline:
                self.stats.incr("shed_deadline")
                self._retire(st, DeadlineExceeded(
                    "deadline expired mid-generation"))
                continue
            if st._cancelled:
                self._retire(st)
                continue
            finished = False
            for tok in emitted:
                if st._last_t is not None:
                    self.stats.token_latency.observe(now - st._last_t)
                st._deliver(tok, now)
                self.stats.incr("decode_tokens_total")
                if (len(st._tokens) >= st.max_new_tokens
                        or tok == st.eos_id):
                    # plain decode would have stopped HERE: tokens past
                    # the eos are discarded, keeping streams identical
                    finished = True
                    break
            if finished:
                self._retire(st)
        if k_live:
            self.stats.set_gauge("spec_adaptive_k",
                                 sum(k_live) / len(k_live))
        self._set_pool_gauges()

    def _retire(self, st, error=None):
        with self._lock:
            if st._slot >= 0 and self._active[st._slot] is st:
                self._active[st._slot] = None
                self._positions[st._slot] = -1
            pages, st._pages = st._pages, None
        if pages:
            self.allocator.free(pages)
        st._finish(error)
        self.stats.incr("decode_retired_total")
        if error is None:
            self.stats.incr("responses_ok")
        self._wake.set()  # freed slot + pages: re-admit immediately
