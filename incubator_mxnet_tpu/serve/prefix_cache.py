"""Copy-on-write prefix cache: a radix tree over token prefixes whose
nodes map to refcounted KV pages.

Serving fleets see the same system prompt, few-shot preamble, or
document header thousands of times; recomputing its KV rows per stream
wastes exactly the prefill FLOPs disaggregation tries to concentrate.
This cache remembers, per page-aligned chunk of a prompt, WHICH KV page
already holds those rows. A hit costs ``PageAllocator.share`` — a
refcount bump, zero data movement — and the stream's page table simply
points at the shared page; the suffix is completed by chunked prefill
(serve/disagg.PrefillPredictor).

Sharing rules (the CoW contract, enforced with PageAllocator):

* A cached FULL page (``page_size`` token rows) is immutable: every
  holder only reads it, so any number of streams share it outright.
* A cached PARTIAL tail page is immutable BELOW its cached length; the
  stream that inserted it retains append rights above (its own decode
  rows land there, never overlapping cached rows). Any OTHER stream
  that matches the tail must write its divergent suffix into that same
  page — so admission takes ``PageAllocator.fork``: the first divergent
  write trades the shared hold for a fresh exclusive copy.
* The cache holds its OWN refcount on every cached page. "Refcount 0"
  in eviction terms means no live STREAM holds the page — i.e. the
  allocator refcount is down to the cache's single hold. LRU eviction
  touches only such pages; a page pinned by a live stream is never
  evicted, so a page table can never dangle.

The tree is a radix tree keyed by page-sized token chunks: lookup walks
child edges chunk by chunk (O(prompt/page_size) dict hops), and partial
tails hang off the last matched full node. Multiple partial tails with
different contents may coexist under one node; lookup picks the longest
one matching the prompt.

Lock hierarchy: the cache's ``self._lock`` is taken first, the
allocator's leaf lock inside it (same direction as DecodeScheduler ->
allocator; the allocator never calls back out, so no cycle exists).
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from .. import util
from .. import mxsan as _mxsan

__all__ = ["PrefixCache"]


class _Node:
    __slots__ = ("chunk", "page", "n_tokens", "children", "parent", "tick")

    def __init__(self, chunk, page, n_tokens, parent):
        self.chunk = chunk          # tuple of token ids this edge covers
        self.page = page            # KV page id holding those rows
        self.n_tokens = n_tokens    # == page_size for full, < for tails
        self.children = {}          # chunk tuple -> _Node (full pages)
        self.parent = parent
        self.tick = 0               # LRU clock at last touch


class PrefixCache:
    """Radix tree over token prefixes -> refcounted KV pages.

    ``allocator`` is the PageAllocator owning the pool the pages live
    in; the cache and every scheduler sharing pages MUST use the same
    allocator instance (page ids are meaningless across pools).
    """

    def __init__(self, allocator, page_size, *, max_pages=None):
        if page_size < 1:
            raise MXNetError("PrefixCache needs page_size >= 1")
        self.allocator = allocator
        self.page_size = int(page_size)
        self.max_pages = int(
            max_pages if max_pages is not None
            else util.getenv_int("MXNET_PREFIX_CACHE_PAGES"))
        self._lock = _mxsan.lock("serve/prefix_cache.py", "self._lock")
        self._root = _Node((), -1, 0, None)
        self._clock = 0
        self._cached_pages = 0
        self._hits = 0
        self._misses = 0
        self._tokens_saved = 0
        self._inserted = 0
        self._evicted = 0
        self._cow_forks = 0

    # -- lookup ---------------------------------------------------------
    def lookup(self, prompt):
        """Longest cached prefix of ``prompt``. Returns
        ``(pages, covered, partial)``: shared page ids in prefix order,
        how many leading tokens they cover, and whether the last page is
        a partial tail (fewer than page_size cached rows — the caller
        must CoW-fork it before writing its suffix into it).

        Every returned page carries a fresh ``share`` hold for the
        caller; release with ``allocator.free`` at stream retire.
        Coverage is capped below ``len(prompt)`` so the suffix prefill
        always has at least the final prompt position to compute (the
        next-token logits come from there).
        """
        prompt = tuple(int(t) for t in prompt)
        ps = self.page_size
        with self._lock:
            self._clock += 1
            node = self._root
            pages = []
            covered = 0
            # full pages: only while a strict suffix remains
            while covered + ps < len(prompt):
                child = node.children.get(prompt[covered:covered + ps])
                if child is None or child.n_tokens != ps:
                    break
                child.tick = self._clock
                pages.append(child.page)
                covered += ps
                node = child
            # longest partial tail still leaving >= 1 suffix token
            best = None
            for chunk, child in node.children.items():
                t = child.n_tokens
                if (t < ps and covered + t < len(prompt)
                        and chunk == prompt[covered:covered + t]
                        and (best is None or t > best.n_tokens)):
                    best = child
            partial = False
            if best is not None:
                best.tick = self._clock
                pages.append(best.page)
                covered += best.n_tokens
                partial = True
            if pages:
                self.allocator.share(pages)
                self._hits += 1
                self._tokens_saved += covered
            else:
                self._misses += 1
        return pages, covered, partial

    # -- insert ---------------------------------------------------------
    def insert(self, prompt, pages, n):
        """Register the first ``n`` prompt tokens' KV pages after a
        prefill: ``pages[i]`` holds rows ``i*ps .. (i+1)*ps-1``. Full
        chunks become radix nodes; a non-aligned remainder becomes a
        partial tail. Chunks already cached are skipped (first insert
        wins — both pages hold identical rows, replacing would churn
        refcounts for nothing). The cache takes its own ``share`` hold
        on every page it registers; inserts that would exceed
        ``max_pages`` first evict LRU unpinned leaves, and when nothing
        is evictable the remainder of the insert is dropped.
        """
        prompt = tuple(int(t) for t in prompt)
        n = min(int(n), len(prompt))
        ps = self.page_size
        with self._lock:
            self._clock += 1
            node = self._root
            for i in range(n // ps):
                chunk = prompt[i * ps:(i + 1) * ps]
                child = node.children.get(chunk)
                if child is not None and child.n_tokens == ps:
                    child.tick = self._clock
                    node = child
                    continue
                if not self._make_room_locked():
                    return
                child = _Node(chunk, int(pages[i]), ps, node)
                self.allocator.share([child.page])
                node.children[chunk] = child
                child.tick = self._clock
                node = child
                self._cached_pages += 1
                self._inserted += 1
            tail = n % ps
            if tail:
                chunk = prompt[n - tail:n]
                for child in node.children.values():
                    if child.n_tokens == tail and child.chunk == chunk:
                        child.tick = self._clock
                        return
                if not self._make_room_locked():
                    return
                child = _Node(chunk, int(pages[n // ps]), tail, node)
                self.allocator.share([child.page])
                node.children[chunk] = child
                child.tick = self._clock
                self._cached_pages += 1
                self._inserted += 1

    def _make_room_locked(self):
        """Evict LRU unpinned leaves until one slot is free. A node is
        evictable only when it is a LEAF (evicting an interior node
        would orphan its descendants' prefix) and no stream holds its
        page (allocator refcount == the cache's own hold)."""
        while self._cached_pages >= self.max_pages:
            victim = None
            stack = [self._root]
            while stack:
                nd = stack.pop()
                for child in nd.children.values():
                    if child.children:
                        stack.append(child)
                    elif self.allocator.refcount(child.page) == 1:
                        if victim is None or child.tick < victim.tick:
                            victim = child
            if victim is None:
                return False
            del victim.parent.children[victim.chunk]
            self.allocator.free([victim.page])
            self._cached_pages -= 1
            self._evicted += 1
        return True

    # -- CoW accounting (the fork itself lives on PageAllocator) --------
    def note_cow_fork(self):
        with self._lock:
            self._cow_forks += 1

    # -- maintenance ----------------------------------------------------
    def clear(self):
        """Drop every cached page (releases the cache's holds; pages
        still pinned by live streams stay live until those retire)."""
        with self._lock:
            pages = []
            stack = [self._root]
            while stack:
                nd = stack.pop()
                for child in nd.children.values():
                    pages.append(child.page)
                    stack.append(child)
            self._root.children.clear()
            self._cached_pages = 0
        if pages:
            self.allocator.free(pages)
        return len(pages)

    def stats(self):
        with self._lock:
            lookups = self._hits + self._misses
            return {"cached_pages": self._cached_pages,
                    "max_pages": self.max_pages,
                    "hits": self._hits,
                    "misses": self._misses,
                    "hit_rate": (self._hits / lookups) if lookups else 0.0,
                    "tokens_saved": self._tokens_saved,
                    "inserted": self._inserted,
                    "evicted": self._evicted,
                    "cow_forks": self._cow_forks}
