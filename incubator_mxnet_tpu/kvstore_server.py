"""Host-side asynchronous parameter server for kvstore type ``dist_async``.

Reference: src/kvstore/kvstore_dist_server.h — a ZeroMQ/ps-lite server
process that owns the weights and, in async mode (AsyncDefault,
kvstore_dist_server.h:346-358), applies the updater to EVERY incoming
gradient immediately, with no per-key barrier across workers: workers run
free, gradients may be stale, pulls return whatever the weights are now.

TPU-native placement: the synchronous path needs no server at all (XLA
collectives over ICI — kvstore.py), but genuine async semantics cannot be
expressed as an SPMD collective, so this module re-creates the reference's
*host-side* control plane: a socket server thread living in the rank-0
process (servers and workers co-locate, like the reference's
``tools/launch.py`` single-machine mode), length-prefixed-pickle protocol,
one handler thread per worker connection, updates serialized by a lock (the
reference's per-key request queue). The device never blocks on this path —
gradients arrive as host numpy buffers, exactly like ps-lite's CPU-side
KVServer.

Wire ops (reference message vocabulary, kvstore_dist_server.h DataHandleEx):
  init            — store an initial weight, first writer wins
  push            — apply updater(key, grad, weight) NOW; returns the
                    server's total push count (per-rank counts observable
                    via ``stats`` — used by tests to prove workers run
                    unbarriered)
  pull            — return the latest weight bytes
  set_optimizer   — install a pickled Optimizer server-side (the reference
                    sends the serialized optimizer to servers,
                    python/mxnet/kvstore.py:450 _send_command_to_servers)
  stats / stop    — introspection / shutdown

Wire security: the payload is pickle, so authentication must happen before
a single byte is unpickled. Each side sends a random 16-byte nonce at
connect time; both derive a per-connection session key
HMAC(token, client_nonce + server_nonce) and every frame carries a
HMAC-SHA256 tag over (direction, per-direction sequence number, payload).
A peer without the cluster token cannot produce a valid tag for even its
first frame, captured frames fail on any other connection (fresh nonces)
or at any other position (sequence number), and in-flight tampering is
detected — unlike the previous one-shot cleartext token handshake, which
a same-network sniffer could replay verbatim. The listener additionally
binds only the coordinator-facing interface (MXNET_KVSTORE_BIND_ADDR to
override), not 0.0.0.0.
"""
from __future__ import annotations

import hashlib
import hmac
import pickle
import secrets
import socket
import struct
import threading

from .base import MXNetError

__all__ = ["AsyncServer", "AsyncClient", "start_async_server",
           "connect_async_server"]

_HDR = struct.Struct("<Q")
_NONCE_LEN = 16
_MAC_LEN = hashlib.sha256().digest_size


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _session_key(token, client_nonce, server_nonce):
    return hmac.new(token.encode(), b"mxtpu-kvstore-v1" + client_nonce +
                    server_nonce, hashlib.sha256).digest()


def _frame_mac(key, direction, seq, payload):
    return hmac.new(key, direction + _HDR.pack(seq) + payload,
                    hashlib.sha256).digest()


class _Channel:
    """One authenticated end of a connection: frames are
    ``len || payload || mac`` with the MAC bound to the session key, the
    frame direction (so a reflected frame never verifies), and a
    per-direction sequence number (so a replayed or reordered frame never
    verifies). ``recv`` raises ConnectionError on a bad MAC BEFORE the
    payload reaches pickle."""

    def __init__(self, sock, key, send_dir, recv_dir):
        self._sock = sock
        self._key = key
        self._send_dir = send_dir
        self._recv_dir = recv_dir
        self._send_seq = 0
        self._recv_seq = 0

    def send(self, obj):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        mac = _frame_mac(self._key, self._send_dir, self._send_seq, payload)
        self._send_seq += 1
        self._sock.sendall(_HDR.pack(len(payload)) + payload + mac)

    def recv(self):
        (n,) = _HDR.unpack(_recv_exact(self._sock, _HDR.size))
        payload = _recv_exact(self._sock, n)
        mac = _recv_exact(self._sock, _MAC_LEN)
        want = _frame_mac(self._key, self._recv_dir, self._recv_seq, payload)
        if not hmac.compare_digest(mac, want):
            raise ConnectionError("frame MAC mismatch")
        self._recv_seq += 1
        return pickle.loads(payload)


def _host_ip():
    """Routable address of this host for the published server endpoint
    (UDP-connect trick; falls back to loopback for single-machine runs)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class AsyncServer:
    """The parameter-server role (reference KVStoreDistServer, async mode)."""

    def __init__(self):
        # every mapping is keyed by (gen, ...): `gen` is the client-side
        # store generation, so a SECOND dist_async KVStore created in the
        # same cluster gets fresh weights/optimizer instead of silently
        # inheriting the previous store's converged state
        self._store = {}            # (gen, key) -> NDArray weight
        self._updaters = {}         # gen -> Updater
        self._lock = threading.Lock()   # serializes updates, like the
        #                                 reference's executor queue
        self._push_counts = {}      # (gen, rank) -> pushes handled
        self._stopped = threading.Event()
        self._sock = None
        self._threads = []
        # per-cluster shared secret: the wire is pickle, so an
        # unauthenticated peer could execute arbitrary code — every
        # connection must present this token (distributed to workers
        # through the jax coordination service, which is already the
        # cluster trust boundary) BEFORE any frame is unpickled
        self.token = secrets.token_hex(16)

    # -- request handling --------------------------------------------------
    def _handle(self, msg):
        from .ndarray.ndarray import NDArray
        import jax.numpy as jnp

        op = msg[0]
        if op == "init":
            _, gen, key, val = msg
            with self._lock:
                # first writer wins WITHIN a generation (every worker
                # inits the same values, reference kvstore_dist.h Init)
                self._store.setdefault((gen, key), NDArray(jnp.asarray(val)))
            return ("ok",)
        if op == "push":
            _, gen, key, grad, rank = msg
            with self._lock:
                if (gen, key) not in self._store:
                    return ("err", f"key {key!r} not initialized")
                stored = self._store[(gen, key)]
                updater = self._updaters.get(gen)
                if updater is not None:
                    # THE async semantics: one update per incoming push,
                    # no cross-worker aggregation or barrier
                    # (kvstore_dist_server.h:346 AsyncDefault)
                    updater(_updater_key(key),
                            NDArray(jnp.asarray(grad)), stored)
                else:
                    # no optimizer installed: replace, the reference
                    # server's CopyFromTo default
                    stored._data = jnp.asarray(grad).astype(stored.dtype)
                ck = (gen, rank)
                self._push_counts[ck] = self._push_counts.get(ck, 0) + 1
                total = sum(n for (g, _), n in self._push_counts.items()
                            if g == gen)
            return ("ok", total)
        if op == "pull":
            _, gen, key = msg
            with self._lock:
                if (gen, key) not in self._store:
                    return ("err", f"key {key!r} not initialized")
                import numpy as np
                return ("ok", np.asarray(self._store[(gen, key)].asnumpy()))
        if op == "set_optimizer":
            _, gen, opt_bytes = msg
            from . import optimizer as opt
            optimizer = pickle.loads(opt_bytes)
            with self._lock:
                if gen in self._updaters:
                    # a second installer (late worker / restart) must not
                    # wipe accumulated momentum/variance state mid-run
                    return ("ok",)
                self._updaters[gen] = opt.get_updater(optimizer)
            return ("ok",)
        if op == "stats":
            _, gen = msg
            with self._lock:
                return ("ok", {r: n for (g, r), n in
                               self._push_counts.items() if g == gen})
        if op == "get_states":
            _, gen, dump_optimizer = msg
            with self._lock:
                updater = self._updaters.get(gen)
                if updater is None:
                    return ("err", "no optimizer set")
                return ("ok",
                        updater.get_states(dump_optimizer=dump_optimizer))
        if op == "set_states":
            _, gen, states = msg
            with self._lock:
                updater = self._updaters.get(gen)
                if updater is None:
                    return ("err", "no optimizer set")
                updater.set_states(states)
            return ("ok",)
        if op == "stop":
            self._stopped.set()
            return ("ok",)
        return ("err", f"unknown op {op!r}")

    # -- socket plumbing ---------------------------------------------------
    def _client_loop(self, conn):
        try:
            # nonce exchange as RAW BYTES, then per-frame HMAC with the
            # derived session key; a peer without the token fails the MAC
            # on its very first frame — nothing is ever unpickled from it
            try:
                client_nonce = _recv_exact(conn, _NONCE_LEN)
                server_nonce = secrets.token_bytes(_NONCE_LEN)
                conn.sendall(server_nonce)
            except (ConnectionError, OSError):
                return
            chan = _Channel(conn,
                            _session_key(self.token, client_nonce,
                                         server_nonce),
                            send_dir=b"S", recv_dir=b"C")
            while not self._stopped.is_set():
                try:
                    msg = chan.recv()       # silent close on MAC mismatch
                except (ConnectionError, OSError):
                    return
                try:
                    reply = self._handle(msg)
                except Exception as e:          # report, don't kill server
                    reply = ("err", repr(e))
                try:
                    chan.send(reply)
                except (ConnectionError, OSError):
                    return
        finally:
            conn.close()

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._client_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def start(self):
        """Bind, start the accept thread, return the advertised addr.

        Binds ONLY the coordinator-facing interface by default (the same
        address the workers are told to dial), so the pickle endpoint is
        not reachable on every interface of the host; MXNET_KVSTORE_BIND_ADDR
        overrides (e.g. '127.0.0.1' for single-machine runs, '0.0.0.0' to
        restore wildcard binding behind a firewall)."""
        from .util import getenv_str
        bind = getenv_str("MXNET_KVSTORE_BIND_ADDR") or _host_ip()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((bind, 0))
        except OSError:
            # interface probe gave an unbindable address (odd netns /
            # no default route): loopback still serves single-machine runs
            bind = "127.0.0.1"
            self._sock.bind((bind, 0))
        self._sock.listen(64)
        port = self._sock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        advertise = _host_ip() if bind in ("0.0.0.0", "::") else bind
        return f"{advertise}:{port}"

    def stop(self):
        self._stopped.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


def _updater_key(key):
    try:
        return int(key)
    except (TypeError, ValueError):
        return key


class AsyncClient:
    """Worker-side connection to the async server (reference KVWorker)."""

    def __init__(self, addr, token):
        host, port = addr.rsplit(":", 1)
        self._lock = threading.Lock()
        self._sock = socket.create_connection((host, int(port)), timeout=120)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # nonce exchange, then every frame is HMAC'd with the session key
        client_nonce = secrets.token_bytes(_NONCE_LEN)
        self._sock.sendall(client_nonce)
        server_nonce = _recv_exact(self._sock, _NONCE_LEN)
        self._chan = _Channel(self._sock,
                              _session_key(token, client_nonce,
                                           server_nonce),
                              send_dir=b"C", recv_dir=b"S")

    def call(self, *msg):
        with self._lock:
            self._chan.send(msg)
            reply = self._chan.recv()
        if reply[0] != "ok":
            raise MXNetError(f"async kvstore server: {reply[1]}")
        return reply[1] if len(reply) > 1 else None

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


_SERVER_SINGLETON = {}


def start_async_server():
    """Start (once per process) the rank-0 server; returns "addr token"
    (one string so it travels as a single coordination-service value)."""
    if "server" not in _SERVER_SINGLETON:
        srv = AsyncServer()
        _SERVER_SINGLETON["server"] = srv
        _SERVER_SINGLETON["addr"] = srv.start()
    srv = _SERVER_SINGLETON["server"]
    return f"{_SERVER_SINGLETON['addr']} {srv.token}"


def connect_async_server(addr_token):
    addr, token = addr_token.split(" ", 1)
    return AsyncClient(addr, token)
