"""Host-side asynchronous parameter server for kvstore type ``dist_async``.

Reference: src/kvstore/kvstore_dist_server.h — a ZeroMQ/ps-lite server
process that owns the weights and, in async mode (AsyncDefault,
kvstore_dist_server.h:346-358), applies the updater to EVERY incoming
gradient immediately, with no per-key barrier across workers: workers run
free, gradients may be stale, pulls return whatever the weights are now.

TPU-native placement: the synchronous path needs no server at all (XLA
collectives over ICI — kvstore.py), but genuine async semantics cannot be
expressed as an SPMD collective, so this module re-creates the reference's
*host-side* control plane: a socket server thread living in the rank-0
process (servers and workers co-locate, like the reference's
``tools/launch.py`` single-machine mode), length-prefixed-pickle protocol,
one handler thread per worker connection, updates serialized by a lock (the
reference's per-key request queue). The device never blocks on this path —
gradients arrive as host numpy buffers, exactly like ps-lite's CPU-side
KVServer.

Wire ops (reference message vocabulary, kvstore_dist_server.h DataHandleEx):
  init            — store an initial weight, first writer wins
  push            — apply updater(key, grad, weight) NOW; returns the
                    server's total push count (per-rank counts observable
                    via ``stats`` — used by tests to prove workers run
                    unbarriered)
  pull            — return the latest weight bytes
  set_optimizer   — install a pickled Optimizer server-side (the reference
                    sends the serialized optimizer to servers,
                    python/mxnet/kvstore.py:450 _send_command_to_servers)
  stats / stop    — introspection / shutdown
  fleet_*         — fleet observability plane (fleetobs.py): heartbeat
                    snapshots fold into a FleetRegistry; fleet_view /
                    fleet_alerts / fleet_metrics read the aggregate,
                    fleet_profile_request queues a remote-profile control
                    op (delivered in the target's heartbeat reply),
                    fleet_profile_push ships the captured trace back and
                    fleet_profile_fetch hands it to the operator
  serve_*         — serving control plane (serve/control_plane.py):
                    ModelServer replicas serve_register
                    (model, generation, buckets, http_addr), refresh
                    liveness + readiness with serve_beat, and
                    serve_deregister on drain; routers pull the ready
                    set with serve_view. Rides the same MAC'd wire, so
                    replica registration inherits the cluster trust
                    boundary

Wire security: the payload is pickle, so authentication must happen before
a single byte is unpickled. Each side sends a random 16-byte nonce at
connect time; both derive a per-connection session key
HMAC(token, client_nonce + server_nonce) and every frame carries a
HMAC-SHA256 tag over (direction, per-direction sequence number, payload).
A peer without the cluster token cannot produce a valid tag for even its
first frame, captured frames fail on any other connection (fresh nonces)
or at any other position (sequence number), and in-flight tampering is
detected — unlike the previous one-shot cleartext token handshake, which
a same-network sniffer could replay verbatim. The listener additionally
binds only the coordinator-facing interface (MXNET_KVSTORE_BIND_ADDR to
override), not 0.0.0.0.
"""
from __future__ import annotations

import hashlib
import hmac
import logging
import pickle
import random
import secrets
import socket
import struct
import threading
import time

from . import fault as _fault
from .base import MXNetError
from . import mxsan as _mxsan

__all__ = ["AsyncServer", "AsyncClient", "start_async_server",
           "connect_async_server"]

_HDR = struct.Struct("<Q")
_NONCE_LEN = 16
_MAC_LEN = hashlib.sha256().digest_size


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _session_key(token, client_nonce, server_nonce):
    return hmac.new(token.encode(), b"mxtpu-kvstore-v1" + client_nonce +
                    server_nonce, hashlib.sha256).digest()


def _frame_mac(key, direction, seq, payload):
    return hmac.new(key, direction + _HDR.pack(seq) + payload,
                    hashlib.sha256).digest()


class _Channel:
    """One authenticated end of a connection: frames are
    ``len || payload || mac`` with the MAC bound to the session key, the
    frame direction (so a reflected frame never verifies), and a
    per-direction sequence number (so a replayed or reordered frame never
    verifies). ``recv`` raises ConnectionError on a bad MAC BEFORE the
    payload reaches pickle."""

    def __init__(self, sock, key, send_dir, recv_dir):
        self._sock = sock
        self._key = key
        self._send_dir = send_dir
        self._recv_dir = recv_dir
        self._send_seq = 0
        self._recv_seq = 0

    def send(self, obj):
        _fault.inject("frame_send")     # MXNET_FAULT_INJECT test hook
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        mac = _frame_mac(self._key, self._send_dir, self._send_seq, payload)
        self._send_seq += 1
        self._sock.sendall(_HDR.pack(len(payload)) + payload + mac)

    def recv(self):
        _fault.inject("frame_recv")     # MXNET_FAULT_INJECT test hook
        (n,) = _HDR.unpack(_recv_exact(self._sock, _HDR.size))
        payload = _recv_exact(self._sock, n)
        mac = _recv_exact(self._sock, _MAC_LEN)
        want = _frame_mac(self._key, self._recv_dir, self._recv_seq, payload)
        if not hmac.compare_digest(mac, want):
            raise ConnectionError("frame MAC mismatch")
        self._recv_seq += 1
        return pickle.loads(payload)


def _host_ip():
    """Routable address of this host for the published server endpoint
    (UDP-connect trick; falls back to loopback for single-machine runs)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class AsyncServer:
    """The parameter-server role (reference KVStoreDistServer, async mode)."""

    def __init__(self):
        # every mapping is keyed by (gen, ...): `gen` is the client-side
        # store generation, so a SECOND dist_async KVStore created in the
        # same cluster gets fresh weights/optimizer instead of silently
        # inheriting the previous store's converged state
        self._store = {}            # (gen, key) -> NDArray weight
        self._updaters = {}         # gen -> Updater
        self._lock = _mxsan.lock(
            "kvstore_server.py", "self._lock")   # serializes updates, like the
        #                                 reference's executor queue
        self._push_counts = {}      # (gen, rank) -> pushes handled
        # liveness registry (reference kvstore_dist.h:121 get_dead_nodes):
        # fed by register/heartbeat/push, read by dead_nodes/membership.
        # _hb_lock is a LEAF lock — never held together with self._lock
        # (push refreshes liveness after releasing the update lock)
        self._hb_lock = _mxsan.lock("kvstore_server.py", "self._hb_lock")
        self._liveness = {}         # (gen, rank) -> (last_monotonic, step)
        self._phase_reports = {}    # (gen, rank) -> {phase: ms} last step
        self._members = {}          # gen -> set of registered ranks
        self._epoch = {}            # gen -> membership epoch (bumps on
        #                             register, i.e. join/rejoin)
        self._stopped = threading.Event()
        self._sock = None
        self._threads = []
        # fleet observability plane (lazy: built on the first fleet
        # snapshot or fleet_* op, so a non-fleet server allocates
        # nothing). Its HTTP surface starts in start() when the plane
        # is enabled; the socket wire serves the same views either way.
        self._fleet = None
        self.fleet_http = None
        self.fleet_http_addr = None
        # serving control plane (lazy like _fleet: built on the first
        # serve_* op, so a training-only server allocates nothing)
        self._serve = None
        # disaggregated-serving page store: ship_key -> (expiry_mono,
        # meta, flat_blob). Prefill replicas kv_page_put exported KV
        # pages here; the target decode replica kv_page_get's them.
        # Entries expire after MXNET_DISAGG_SHIP_TTL seconds (lazily
        # collected on access) so an orphaned handoff cannot pin bytes.
        self._page_store = {}
        self._page_bytes_in = 0
        self._page_puts = 0
        self._page_gets = 0
        # per-cluster shared secret: the wire is pickle, so an
        # unauthenticated peer could execute arbitrary code — every
        # connection must present this token (distributed to workers
        # through the jax coordination service, which is already the
        # cluster trust boundary) BEFORE any frame is unpickled
        self.token = secrets.token_hex(16)

    # -- request handling --------------------------------------------------
    def _handle(self, msg):
        from .ndarray.ndarray import NDArray
        import jax.numpy as jnp

        op = msg[0]
        if op == "init":
            _, gen, key, val = msg
            with self._lock:
                # first writer wins WITHIN a generation (every worker
                # inits the same values, reference kvstore_dist.h Init)
                self._store.setdefault((gen, key), NDArray(jnp.asarray(val)))
            return ("ok",)
        if op == "push":
            _, gen, key, grad, rank = msg
            with self._lock:
                if (gen, key) not in self._store:
                    return ("err", f"key {key!r} not initialized")
                stored = self._store[(gen, key)]
                updater = self._updaters.get(gen)
                if updater is not None:
                    # THE async semantics: one update per incoming push,
                    # no cross-worker aggregation or barrier
                    # (kvstore_dist_server.h:346 AsyncDefault)
                    updater(_updater_key(key),
                            NDArray(jnp.asarray(grad)), stored)
                else:
                    # no optimizer installed: replace, the reference
                    # server's CopyFromTo default
                    stored._data = jnp.asarray(grad).astype(stored.dtype)
                ck = (gen, rank)
                self._push_counts[ck] = self._push_counts.get(ck, 0) + 1
                total = sum(n for (g, _), n in self._push_counts.items()
                            if g == gen)
            with self._hb_lock:     # a push proves liveness too (taken
                #                     AFTER _lock is released, never nested)
                if (gen, rank) in self._liveness:
                    step = self._liveness[(gen, rank)][1]
                    self._liveness[(gen, rank)] = (time.monotonic(), step)
            return ("ok", total)
        if op == "pull":
            _, gen, key = msg
            with self._lock:
                if (gen, key) not in self._store:
                    return ("err", f"key {key!r} not initialized")
                import numpy as np
                return ("ok", np.asarray(self._store[(gen, key)].asnumpy()))
        if op == "set_optimizer":
            _, gen, opt_bytes = msg
            from . import optimizer as opt
            optimizer = pickle.loads(opt_bytes)
            with self._lock:
                if gen in self._updaters:
                    # a second installer (late worker / restart) must not
                    # wipe accumulated momentum/variance state mid-run
                    return ("ok",)
                self._updaters[gen] = opt.get_updater(optimizer)
            return ("ok",)
        if op == "stats":
            _, gen = msg
            with self._lock:
                return ("ok", {r: n for (g, r), n in
                               self._push_counts.items() if g == gen})
        if op == "get_states":
            _, gen, dump_optimizer = msg
            with self._lock:
                updater = self._updaters.get(gen)
                if updater is None:
                    return ("err", "no optimizer set")
                return ("ok",
                        updater.get_states(dump_optimizer=dump_optimizer))
        if op == "set_states":
            _, gen, states = msg
            with self._lock:
                updater = self._updaters.get(gen)
                if updater is None:
                    return ("err", "no optimizer set")
                updater.set_states(states)
            return ("ok",)
        if op == "register":
            # elastic membership: assign (or reclaim) a rank. A rank_hint
            # naming a DEAD rank reclaims that identity — the respawned
            # replacement for a kill -9'd worker; a hint naming a LIVE
            # rank gets a fresh one instead (never steal an identity)
            _, gen, rank_hint = msg
            from .util import getenv_int
            timeout = getenv_int("MXNET_DEAD_NODE_TIMEOUT")
            with self._hb_lock:
                members = self._members.setdefault(gen, set())
                now = time.monotonic()
                rejoined = False
                rank = rank_hint
                if rank is not None and rank in members:
                    last = self._liveness.get((gen, rank), (0.0, 0))[0]
                    if now - last > timeout:
                        rejoined = True
                    else:
                        rank = None
                if rank is None:
                    rank = 0
                    while rank in members:
                        rank += 1
                members.add(rank)
                self._liveness[(gen, rank)] = (now, 0)
                self._epoch[gen] = self._epoch.get(gen, 0) + 1
                return ("ok", {"rank": rank, "epoch": self._epoch[gen],
                               "num_workers": len(members),
                               "rejoined": rejoined})
        if op == "heartbeat":
            # liveness beat; the reply carries the membership epoch so
            # every worker learns of joins/rejoins within one beat period.
            # v2 senders append the last step's {phase: ms} vector (the
            # straggler report names WHICH phase is slow on which rank) and
            # get a dict reply that also carries the server wall clock for
            # client-side clock-offset estimation (tools/trace_merge.py);
            # v1 senders keep the original 4-tuple / int-epoch shape.
            # MXNET_FLEET_OBS senders append a sixth element — the bounded
            # metric snapshot — folded into the FleetRegistry AFTER
            # _hb_lock is released (registry lock and _hb_lock never nest);
            # a pending control op for the rank rides back in the reply.
            phases = snap = None
            if len(msg) >= 5:
                phases = msg[4]
            if len(msg) >= 6:
                snap = msg[5]
            _, gen, rank, step = msg[:4]
            with self._hb_lock:
                self._members.setdefault(gen, set()).add(rank)
                self._liveness[(gen, rank)] = (time.monotonic(), int(step))
                epoch = self._epoch.setdefault(gen, 1)
                if phases is None and snap is None:
                    return ("ok", epoch)
                if phases is not None:
                    self._phase_reports[(gen, rank)] = dict(phases)
            reply = {"epoch": epoch, "server_time": time.time()}
            if snap is not None:
                cmd = self._fleet_registry().fold(gen, rank, step, snap)
                if cmd is not None:
                    reply["fleet"] = cmd
            return ("ok", reply)
        if op == "fleet_view":
            return ("ok", self._fleet_registry().fleet_view())
        if op == "fleet_alerts":
            return ("ok", self._fleet_registry().alerts_view())
        if op == "fleet_metrics":
            return ("ok", self._fleet_registry().render_prometheus())
        if op == "fleet_profile_request":
            _, gen, rank, steps = msg
            return ("ok",
                    self._fleet_registry().request_profile(gen, rank, steps))
        if op == "fleet_profile_push":
            _, gen, rank, request_id, payload = msg
            try:
                self._fleet_registry().store_profile(gen, rank,
                                                     request_id, payload)
            except ValueError as e:
                return ("err", str(e))
            return ("ok",)
        if op == "fleet_profile_fetch":
            _, gen, rank = msg
            return ("ok", self._fleet_registry().fetch_profile(gen, rank))
        if op == "dead_nodes":
            _, gen, timeout = msg
            with self._hb_lock:
                return ("ok", self._dead_locked(gen, timeout))
        if op == "membership":
            _, gen, timeout, lag = msg
            with self._hb_lock:
                members = sorted(self._members.get(gen, ()))
                dead = self._dead_locked(gen, timeout)
                steps = {r: self._liveness.get((gen, r), (0.0, 0))[1]
                         for r in members}
                top = max(steps.values(), default=0)
                stragglers = sorted(
                    r for r in members
                    if r not in dead and top - steps[r] >= lag
                ) if lag > 0 else []
                # per-rank phase vectors from v2 heartbeats: the straggler
                # report can name WHICH phase dominates on a slow rank
                phases = {r: self._phase_reports[(gen, r)] for r in members
                          if self._phase_reports.get((gen, r))}
                slow_phase = {r: max(v, key=v.get)
                              for r, v in phases.items()}
                return ("ok", {"epoch": self._epoch.setdefault(gen, 1),
                               "workers": members, "dead": dead,
                               "stragglers": stragglers, "steps": steps,
                               "phases": phases, "slow_phase": slow_phase})
        if op == "serve_register":
            # v2 senders append the replica's role (prefill/decode/both);
            # v1 frames keep the 6-tuple and default to "both"
            _, model, replica_id, generation, buckets, http_addr = msg[:6]
            role = msg[6] if len(msg) > 6 else "both"
            return ("ok", self._serve_registry().register(
                model, replica_id, generation, buckets, http_addr,
                role=role))
        if op == "serve_beat":
            # v2 senders append a load report dict (kv page headroom for
            # the router's decode placement); v1 frames are 6-tuples
            _, model, replica_id, generation, ready, draining = msg[:6]
            load = msg[6] if len(msg) > 6 else None
            return ("ok", self._serve_registry().beat(
                model, replica_id, generation, ready, draining,
                load=load))
        if op == "kv_page_put":
            _, key, meta, blob = msg
            from .util import getenv_int
            ttl = getenv_int("MXNET_DISAGG_SHIP_TTL")
            size = getattr(blob, "nbytes", len(blob))
            with self._lock:
                self._page_store_gc_locked()
                self._page_store[key] = (time.monotonic() + ttl, meta, blob)
                self._page_puts += 1
                self._page_bytes_in += size
            return ("ok", {"stored": True, "bytes": int(size)})
        if op == "kv_page_get":
            # non-destructive by default: a decode replica that dies
            # after fetching must leave the bundle for the retry; the
            # router's whole-stream retry re-fetches the same key.
            _, key = msg[:2]
            delete = bool(msg[2]) if len(msg) > 2 else False
            with self._lock:
                self._page_store_gc_locked()
                row = self._page_store.get(key)
                if row is not None:
                    self._page_gets += 1
                    if delete:
                        del self._page_store[key]
            if row is None:
                return ("ok", None)
            return ("ok", {"meta": row[1], "blob": row[2]})
        if op == "kv_page_del":
            _, key = msg
            with self._lock:
                dropped = self._page_store.pop(key, None) is not None
            return ("ok", {"dropped": dropped})
        if op == "kv_page_stats":
            with self._lock:
                self._page_store_gc_locked()
                return ("ok", {"entries": len(self._page_store),
                               "puts": self._page_puts,
                               "gets": self._page_gets,
                               "bytes_in": self._page_bytes_in})
        if op == "serve_deregister":
            _, model, replica_id = msg
            return ("ok", self._serve_registry().deregister(
                model, replica_id))
        if op == "serve_view":
            _, model = msg
            return ("ok", self._serve_registry().view(model))
        if op == "stop":
            self._stopped.set()
            return ("ok",)
        return ("err", f"unknown op {op!r}")

    def _fleet_registry(self):
        """Lazily build the FleetRegistry (first fleet snapshot or
        fleet_* op); cheap double-checked create — a duplicate build
        under race is harmless, the attribute write is atomic."""
        if self._fleet is None:
            from . import fleetobs as _fobs
            self._fleet = _fobs.FleetRegistry()
        return self._fleet

    def _serve_registry(self):
        """Lazily build the serving-replica registry (first serve_* op);
        same cheap double-checked create as _fleet_registry."""
        if self._serve is None:
            from .serve.control_plane import ServeRegistry
            self._serve = ServeRegistry()
        return self._serve

    def _page_store_gc_locked(self):
        """Drop expired KV-page bundles (caller holds self._lock)."""
        now = time.monotonic()
        dead = [k for k, (exp, _, _) in self._page_store.items()
                if now > exp]
        for k in dead:
            del self._page_store[k]

    def _dead_locked(self, gen, timeout):
        """Registered ranks with no beat/push within `timeout` seconds,
        judged by THIS host's monotonic clock (caller holds _hb_lock)."""
        now = time.monotonic()
        return sorted(
            r for r in self._members.get(gen, ())
            if now - self._liveness.get((gen, r), (0.0, 0))[0] > timeout)

    # -- socket plumbing ---------------------------------------------------
    def _client_loop(self, conn):
        from . import profiler as _prof
        try:
            # nonce exchange as RAW BYTES, then per-frame HMAC with the
            # derived session key; a peer without the token fails the MAC
            # on its very first frame — nothing is ever unpickled from it
            try:
                client_nonce = _recv_exact(conn, _NONCE_LEN)
                server_nonce = secrets.token_bytes(_NONCE_LEN)
                conn.sendall(server_nonce)
            except (ConnectionError, OSError):
                return
            chan = _Channel(conn,
                            _session_key(self.token, client_nonce,
                                         server_nonce),
                            send_dir=b"S", recv_dir=b"C")
            while not self._stopped.is_set():
                try:
                    msg = chan.recv()       # silent close on MAC mismatch
                except (ConnectionError, OSError):
                    return
                # trace-header unwrap: v2 clients wrap the op tuple as
                # ("__v2__", {"trace", "span"}, msg) INSIDE the pickled
                # payload, so the existing frame MAC covers the header —
                # a tampered header fails authentication before unpickle.
                # v1 clients send the plain tuple and dispatch unchanged.
                hdr = None
                if (isinstance(msg, tuple) and len(msg) == 3
                        and msg[0] == "__v2__" and isinstance(msg[1], dict)):
                    hdr, msg = msg[1], msg[2]
                try:
                    if hdr is not None and _prof.attribution_enabled():
                        # handler span linked to the worker-side span id
                        # carried on the wire (merged-timeline join key);
                        # request-trace ids, when riding the envelope,
                        # become link_req_* args so trace_merge can join
                        # the store's work to the originating request
                        args = {"link_trace": hdr.get("trace"),
                                "link_span": hdr.get("span")}
                        if hdr.get("req_trace") is not None:
                            args["link_req_trace"] = hdr["req_trace"]
                            args["link_req_span"] = hdr.get("req_span")
                        with _prof.span(f"server:{msg[0]}", args=args):
                            reply = self._handle(msg)
                    else:
                        reply = self._handle(msg)
                except Exception as e:          # report, don't kill server
                    reply = ("err", repr(e))
                try:
                    chan.send(reply)
                except (ConnectionError, OSError):
                    return
        finally:
            conn.close()

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._client_loop, args=(conn,),
                                 name="mxtpu-kv-client", daemon=True)
            t.start()
            self._threads.append(t)

    def start(self):
        """Bind, start the accept thread, return the advertised addr.

        Binds ONLY the coordinator-facing interface by default (the same
        address the workers are told to dial), so the pickle endpoint is
        not reachable on every interface of the host; MXNET_KVSTORE_BIND_ADDR
        overrides (e.g. '127.0.0.1' for single-machine runs, '0.0.0.0' to
        restore wildcard binding behind a firewall)."""
        from .util import getenv_str
        bind = getenv_str("MXNET_KVSTORE_BIND_ADDR") or _host_ip()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((bind, 0))
        except OSError:
            # interface probe gave an unbindable address (odd netns /
            # no default route): loopback still serves single-machine runs
            bind = "127.0.0.1"
            self._sock.bind((bind, 0))
        self._sock.listen(64)
        port = self._sock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop,
                             name="mxtpu-kv-accept", daemon=True)
        t.start()
        self._threads.append(t)
        advertise = _host_ip() if bind in ("0.0.0.0", "::") else bind
        from . import fleetobs as _fobs
        if _fobs.enabled() and self.fleet_http is None:
            # the coordinator's operator surface: fleet /metrics, /fleet,
            # /alerts on an ephemeral loopback-or-bind-addr HTTP port
            try:
                self.fleet_http = _fobs.start_http(
                    self._fleet_registry(), host=bind)
                h, p = self.fleet_http.server_address[:2]
                self.fleet_http_addr = f"{h}:{p}"
                logging.info("fleet observability HTTP at %s",
                             self.fleet_http_addr)
            except OSError:
                logging.exception("fleet HTTP endpoint failed to start")
        return f"{advertise}:{port}"

    def stop(self):
        self._stopped.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self.fleet_http is not None:
            from . import fleetobs as _fobs
            _fobs.stop_http(self.fleet_http)
            self.fleet_http = None


def _updater_key(key):
    try:
        return int(key)
    except (TypeError, ValueError):
        return key


def _reqtrace_fields():
    """Request-trace wire fields (``req_trace``/``req_span``) or None.

    Looked up via sys.modules so a worker that never imported the serving
    plane pays nothing; with the MXNET_REQTRACE gate off (or no request
    in flight on this thread) this returns None and the frame stays the
    plain pickled tuple.
    """
    import sys
    rt = sys.modules.get(__package__ + ".serve.reqtrace")
    if rt is None:
        return None
    try:
        return rt.wire_fields() or None
    except Exception:
        return None


def _wire_envelope(msg):
    """Wrap the op tuple in the v2 ``("__v2__", hdr, msg)`` envelope when
    step attribution and/or request tracing is live; with both gates off
    the plain tuple goes out — byte-identical to a v1 client's frame."""
    from . import profiler as _prof
    hdr = None
    if _prof.attribution_enabled():
        span = _prof.current_span_id()
        hdr = {"trace": _prof.trace_id(),
               "span": span if span is not None else _prof.next_span_id()}
    req = _reqtrace_fields()
    if req:
        if hdr is None:
            hdr = {"trace": _prof.trace_id(),
                   "span": _prof.next_span_id()}
        hdr.update(req)
    return msg if hdr is None else ("__v2__", hdr, msg)


class AsyncClient:
    """Worker-side connection to the async server (reference KVWorker).

    A dead or wedged server can no longer hang a worker forever: dialing
    uses MXNET_KVSTORE_CONNECT_TIMEOUT, every call is bounded by
    MXNET_KVSTORE_CALL_TIMEOUT on the socket, and both paths retry up to
    MXNET_KVSTORE_RETRIES times over a FRESH connection with exponential
    backoff (MXNET_KVSTORE_RETRY_BACKOFF_MS initial, doubling, capped at
    10s) before raising a clear MXNetError naming the budget spent. Each
    client jitters its schedule by a per-client uniform [0.5, 1.5)
    factor (MXNET_KVSTORE_RETRY_JITTER to disable): after a coordinator
    restart a whole fleet would otherwise redial in lockstep at exactly
    backoff * 2^k — the thundering herd the jitter de-synchronizes.

    At-least-once caveat: a call that timed out may still have been
    applied by the server before the retry lands (e.g. a push counted
    twice). The async semantics already tolerate duplicate gradients —
    they are indistinguishable from one more unbarriered push — but tests
    must not assert exact per-rank push counts under fault injection.
    """

    def __init__(self, addr, token):
        from .util import getenv_bool, getenv_int
        self._addr = addr
        self._token = token
        # mxsan site "AsyncClient._lock" keeps the connection lock (held
        # across socket I/O by design, BLOCKING_OK) distinct from the
        # server's update lock, which shares the self._lock spelling.
        self._lock = _mxsan.lock("kvstore_server.py", "AsyncClient._lock")
        self._sock = None
        self._chan = None
        self._connect_timeout = getenv_int("MXNET_KVSTORE_CONNECT_TIMEOUT")
        self._call_timeout = getenv_int("MXNET_KVSTORE_CALL_TIMEOUT")
        self._retries = max(0, getenv_int("MXNET_KVSTORE_RETRIES"))
        self._backoff_ms = max(
            1, getenv_int("MXNET_KVSTORE_RETRY_BACKOFF_MS"))
        # per-client RNG (os.urandom-seeded): two clients built in the
        # same instant must still draw different retry schedules
        self._rng = random.Random() \
            if getenv_bool("MXNET_KVSTORE_RETRY_JITTER") else None
        with self._lock:
            last = None
            for attempt in range(self._retries + 1):
                if attempt:
                    time.sleep(self._backoff_s(attempt))
                try:
                    self._dial_locked()
                    return
                except (ConnectionError, OSError) as e:
                    last = e
                    self._close_locked()
            raise MXNetError(
                f"async kvstore server at {self._addr} unreachable after "
                f"{self._retries + 1} connect attempts "
                f"(MXNET_KVSTORE_CONNECT_TIMEOUT={self._connect_timeout}s, "
                f"MXNET_KVSTORE_RETRIES={self._retries}): {last!r}")

    def _backoff_s(self, attempt):
        base = min(10.0, self._backoff_ms / 1e3 * (2 ** (attempt - 1)))
        if self._rng is None:
            return base
        return min(10.0, base * self._rng.uniform(0.5, 1.5))

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._chan = None

    def _dial_locked(self):
        host, port = self._addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=self._connect_timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # nonce exchange, then every frame is HMAC'd with the session
            # key (the connect timeout also bounds the exchange)
            client_nonce = secrets.token_bytes(_NONCE_LEN)
            sock.sendall(client_nonce)
            server_nonce = _recv_exact(sock, _NONCE_LEN)
        except BaseException:
            sock.close()
            raise
        sock.settimeout(self._call_timeout)
        self._sock = sock
        self._chan = _Channel(sock,
                              _session_key(self._token, client_nonce,
                                           server_nonce),
                              send_dir=b"C", recv_dir=b"S")

    def call(self, *msg):
        # protocol v2: a trace/span header travels INSIDE the pickled
        # payload so the frame MAC authenticates it; the span id is the
        # caller's innermost active span (the worker-side pushpull span),
        # letting the server's handler span link back to it.  Request
        # traces ride the same envelope as req_trace/req_span fields.
        wire = _wire_envelope(msg)
        last = None
        reply = None
        with self._lock:
            for attempt in range(self._retries + 1):
                if attempt:
                    time.sleep(self._backoff_s(attempt))
                try:
                    if self._chan is None:
                        self._dial_locked()
                    self._chan.send(wire)
                    reply = self._chan.recv()
                    break
                except (ConnectionError, OSError) as e:     # timeout /
                    last = e        # reset / MAC mismatch / injected drop:
                    self._close_locked()    # retry over a fresh connection
            else:
                raise MXNetError(
                    f"async kvstore call {msg[0]!r} to {self._addr} failed "
                    f"after {self._retries + 1} attempts "
                    f"(MXNET_KVSTORE_CALL_TIMEOUT={self._call_timeout}s, "
                    f"MXNET_KVSTORE_RETRIES={self._retries}): {last!r}")
        if reply[0] != "ok":
            # the server ANSWERED with an application error: never retried
            raise MXNetError(f"async kvstore server: {reply[1]}")
        return reply[1] if len(reply) > 1 else None

    def close(self):
        with self._lock:
            self._close_locked()


_SERVER_SINGLETON = {}


def start_async_server():
    """Start (once per process) the rank-0 server; returns "addr token"
    (one string so it travels as a single coordination-service value)."""
    if "server" not in _SERVER_SINGLETON:
        srv = AsyncServer()
        _SERVER_SINGLETON["server"] = srv
        _SERVER_SINGLETON["addr"] = srv.start()
    srv = _SERVER_SINGLETON["server"]
    return f"{_SERVER_SINGLETON['addr']} {srv.token}"


def connect_async_server(addr_token):
    addr, token = addr_token.split(" ", 1)
    return AsyncClient(addr, token)
