"""Failure handling: checkpoint/resume + preemption + liveness (SURVEY §5.3).

The reference's failure story is thin — ps-lite node timeouts surface as
`kv.get_dead_nodes(timeout)` (src/kvstore/kvstore_dist.h:121) and a
restart-recovery flag skips the startup barrier; there is no automatic
checkpoint-resume orchestration. On TPU pods preemption is routine, so
this module goes further:

- ``CheckpointManager``: atomic (write-tmp + rename), rotating, resumable
  checkpoints of net parameters + trainer state, with a sha256-checksummed
  manifest that survives partial writes AND detects silent corruption —
  ``restore()`` falls back to the newest intact generation instead of
  crashing on (or loading) a torn file.
- ``AsyncCheckpointManager``: write-behind checkpointing. ``save_async``
  snapshots parameters to host and returns; a background writer thread
  pays the fsync'd disk write, so a slow disk never stalls a train step.
  The bounded queue drops the OLDEST pending snapshot when full (newest
  state wins). Snapshots carry a ``data_state`` cursor (the prefetcher /
  data-iterator position) so resume is mid-epoch exact.
- ``PreemptionHandler``: SIGTERM/SIGINT hook that flips a flag (and
  optionally checkpoints immediately) so training loops can exit cleanly
  at the next step boundary.
- ``get_dead_nodes``: REAL liveness (reference kvstore_dist.h:121): newest
  registered distributed KVStore's heartbeat registry answers — the
  dist_async server's monotonic clock, or the coordination-service
  generation watch for dist_sync. Single-process: [].
- ``FaultInjector`` / ``inject``: deterministic test-only fault injection
  driven by ``MXNET_FAULT_INJECT`` (worker kills, dropped/delayed wire
  frames, slow checkpoint writes) so the recovery paths above are
  exercisable from any test without monkeypatching.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import signal
import tempfile
import threading
import time
import traceback
import weakref
from collections import deque

from .base import MXNetError
from . import mxsan as _mxsan

__all__ = ["CheckpointManager", "AsyncCheckpointManager", "PreemptionHandler",
           "get_dead_nodes", "resume_or_start", "FaultInjector", "inject",
           "set_fault_spec", "stats", "flight_enabled", "flight_record",
           "flight_dump", "flight_reset"]

_log = logging.getLogger("incubator_mxnet_tpu.fault")


# ---------------------------------------------------------------------------
# fault-tolerance counters (profiler.dumps() "fault" section and the
# mxnet_worker_* Prometheus families read this registry)
# ---------------------------------------------------------------------------

_stats_lock = _mxsan.lock("fault.py", "_stats_lock")
_counters = {
    "ckpt_saves": 0,            # snapshots committed to disk (sync + async)
    "ckpt_async_snapshots": 0,  # save_async calls accepted into the queue
    "ckpt_dropped": 0,          # pending snapshots dropped by the bounded queue
    "ckpt_write_ms": 0.0,       # cumulative background write wall time
    "ckpt_errors": 0,           # background write failures (degraded, logged)
    "ckpt_fallbacks": 0,        # corrupt generations skipped by restore()
    "ckpt_last_step": 0,        # newest step committed to disk
    "heartbeats_sent": 0,       # liveness beats sent by this process
    "dead_nodes_seen": 0,       # cumulative dead ranks reported to callers
    "stragglers_seen": 0,       # cumulative straggler ranks reported
    "rejoins": 0,               # elastic re-registrations after a loss
    "membership_changes": 0,    # server membership epoch changes observed
    "faults_injected": 0,       # MXNET_FAULT_INJECT actions fired
    "slo_alerts": 0,            # fleet SLO alerts raised (fleetobs engine)
}


def _bump(name, delta=1):
    with _stats_lock:
        _counters[name] += delta


def stats():
    """Snapshot of the fault-tolerance counters (profiler.dumps 'fault'
    section, /metrics mxnet_worker_* families, tools/diagnose.py)."""
    with _stats_lock:
        return dict(_counters)


def _reset_stats():
    """Test hook: zero the counter registry."""
    with _stats_lock:
        for k in _counters:
            _counters[k] = 0.0 if k == "ckpt_write_ms" else 0


# ---------------------------------------------------------------------------
# fault injection (MXNET_FAULT_INJECT — the reusable test helper)
# ---------------------------------------------------------------------------

class FaultInjector:
    """Deterministic fault injection for tests.

    Spec grammar (``MXNET_FAULT_INJECT``): ``site@n:action[,...]`` — the
    action fires on the n-th (1-based) ``fire(site)`` call. Actions:

    - ``kill``            — SIGKILL this process (the kill -9 oracle)
    - ``drop``            — raise ConnectionError (a lost wire frame)
    - ``delay=SECONDS``   — sleep (a wedged peer / slow disk)

    Sites wired in-tree: ``push`` (every kvstore push), ``frame_send`` /
    ``frame_recv`` (every authenticated dist_async wire frame),
    ``step`` (every TrainStep call), ``ckpt_write`` (every background
    checkpoint write), ``route`` (every router HTTP attempt against a
    serving replica — drop exercises retry/breaker, delay exercises
    hedging), ``rollout`` (every RolloutManager wave — kill is the
    mid-rollout operator death, delay a wedged wave), ``decode`` (every
    continuous-batching decode step, fired BEFORE the device call —
    kill is the replica dying mid-stream with tokens already flushed,
    the postmortem + router-failover chaos drill). Empty spec =
    zero per-call overhead.
    """

    def __init__(self, spec=None):
        if spec is None:
            from .util import getenv_str
            spec = getenv_str("MXNET_FAULT_INJECT")
        self._lock = _mxsan.lock("fault.py", "self._lock")
        self._hits = {}
        self._rules = {}        # site -> [(n, action, arg)]
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                where, action = part.split(":", 1)
                site, n = where.split("@", 1)
                arg = 0.0
                if action.startswith("delay="):
                    action, arg = "delay", float(action.split("=", 1)[1])
                if action not in ("kill", "drop", "delay"):
                    raise ValueError(action)
                self._rules.setdefault(site.strip(), []).append(
                    (int(n), action, arg))
            except (ValueError, IndexError):
                raise MXNetError(
                    f"bad MXNET_FAULT_INJECT clause {part!r}; expected "
                    "'site@n:kill|drop|delay=SECONDS'")

    @property
    def active(self):
        return bool(self._rules)

    def fire(self, site):
        """Count a hit at `site` and run any action scheduled for it."""
        if not self._rules:
            return
        with self._lock:
            hits = self._hits[site] = self._hits.get(site, 0) + 1
            actions = [r for r in self._rules.get(site, ()) if r[0] == hits]
        if actions:
            # SIGKILL is uncatchable: the flight dump must land on disk
            # before the action loop runs, not in an atexit/finally.
            flight_dump(f"fault:{site}#{hits}")
        for _, action, arg in actions:
            _bump("faults_injected")
            _log.warning("fault injected: %s #%d -> %s", site, hits, action)
            if action == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif action == "drop":
                raise ConnectionError(
                    f"injected frame drop at {site} hit #{hits}")
            elif action == "delay":
                time.sleep(arg)


_injector = None


def _get_injector():
    global _injector
    if _injector is None:
        _injector = FaultInjector()
    return _injector


def set_fault_spec(spec):
    """(Re)configure the process-wide injector from a spec string (tests;
    production processes configure via MXNET_FAULT_INJECT at startup)."""
    global _injector
    _injector = FaultInjector(spec)
    return _injector


def inject(site):
    """Hot-path hook: no-op unless a fault spec is configured."""
    inj = _get_injector()
    if inj.active:
        inj.fire(site)


# ---------------------------------------------------------------------------
# crash flight recorder: a bounded ring of the last N step records/events,
# dumped atomically on SIGUSR1, on a FaultInjector trip (BEFORE the action
# runs — SIGKILL is uncatchable, so the dying worker's postmortem is written
# pre-mortem), and on unhandled exception in TrainStep.run_epoch. Gated on
# MXNET_FLIGHT_RECORDER (a directory path) with the cached-boolean pattern.
# ---------------------------------------------------------------------------

_flight_lock = _mxsan.lock(
    "fault.py", "_flight_lock")     # guards the ring; LEAF, nests under none
_flight_dir = None                  # cached MXNET_FLIGHT_RECORDER read
_flight_ring = None                 # deque of recent records
_flight_sig_installed = False


def flight_enabled():
    """True when the flight recorder is on (MXNET_FLIGHT_RECORDER names a
    dump directory). Read once and cached — the gate sits on the per-step
    hot path."""
    global _flight_dir
    if _flight_dir is None:
        from .util import getenv_str
        _flight_dir = getenv_str("MXNET_FLIGHT_RECORDER") or ""
    return bool(_flight_dir)


def flight_reset():
    """Forget the cached MXNET_FLIGHT_RECORDER read and drop the ring —
    the next flight_enabled() consults the environment again (tests)."""
    global _flight_dir, _flight_ring, _flight_sig_installed
    with _flight_lock:
        _flight_dir = None
        _flight_ring = None
    _flight_sig_installed = False


def _flight_ring_locked():
    global _flight_ring
    if _flight_ring is None:
        from .util import getenv_int
        _flight_ring = deque(maxlen=max(
            getenv_int("MXNET_FLIGHT_RECORDER_SIZE"), 8))
    return _flight_ring


def _flight_install_signal():
    """Lazy SIGUSR1 hook (kill -USR1 <pid> -> postmortem dump of a live
    but wedged worker). Main-thread only — signal.signal raises from
    worker threads, and a recorder must never break its host."""
    global _flight_sig_installed
    if _flight_sig_installed:
        return
    _flight_sig_installed = True
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        signal.signal(signal.SIGUSR1,
                      lambda signum, frame: flight_dump("SIGUSR1"))
    except (ValueError, OSError, AttributeError):
        pass


def flight_record(kind, **data):
    """Append one record to the flight ring (drop-oldest past
    MXNET_FLIGHT_RECORDER_SIZE). No-op when the recorder is off; never
    raises — recording must not take down the step loop."""
    if not flight_enabled():
        return
    try:
        rec = {"t": time.time(), "kind": str(kind)}
        rec.update({k: v for k, v in data.items() if v is not None})
        with _flight_lock:
            _flight_ring_locked().append(rec)
        _flight_install_signal()
    except Exception:       # noqa: BLE001
        pass


def flight_dump(reason):
    """Write the postmortem JSON atomically (private tmp + fsync +
    os.replace, the CheckpointManager idiom) to
    ``$MXNET_FLIGHT_RECORDER/flight-<pid>.json``: the ring, the step
    attribution registry, and the fault counters. Returns the path, or
    None when the recorder is off or the write failed (logged, never
    raised — this runs on dying processes and in signal handlers)."""
    if not flight_enabled():
        return None
    try:
        from . import profiler as _prof
        with _flight_lock:
            ring = list(_flight_ring_locked())
        payload = {
            "reason": str(reason),
            "time": time.time(),
            "pid": os.getpid(),
            "records": ring,
            "fault_stats": stats(),
        }
        try:
            payload["phase_stats"] = _prof.phase_stats()
            payload["last_step_phases"] = _prof.last_step_phases()
            payload["trace_id"] = _prof.trace_id()
        except Exception:       # noqa: BLE001
            pass
        os.makedirs(_flight_dir, exist_ok=True)
        path = os.path.join(_flight_dir, f"flight-{os.getpid()}.json")
        fd, tmp = tempfile.mkstemp(dir=_flight_dir, prefix=".flight.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return path
    except Exception:       # noqa: BLE001
        _log.warning("flight recorder dump failed", exc_info=True)
        return None


# ---------------------------------------------------------------------------
# checkpointing
# ---------------------------------------------------------------------------

def _digest(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _snapshot_params(net):
    """Host copy of a gluon net's parameters under the same structured
    names save_parameters writes — the device->host sync is the ONLY
    step-blocking cost of an async checkpoint."""
    return {k: p.data().asnumpy()
            for k, p in net._collect_params_with_prefix().items()
            if p._data is not None}


class CheckpointManager:
    """Atomic rotating checkpoints for (net, trainer).

    Layout: ``{dir}/{prefix}-{step:08d}.params`` (+ ``.states`` when a
    trainer is given) and a ``{prefix}.manifest.json`` that is only
    updated AFTER the artifact files are fully on disk — a crash mid-save
    never corrupts the latest restorable step. Every manifest entry
    records the artifacts' sha256 + byte sizes, so ``restore()`` detects
    truncation/bit-rot and falls back to the newest INTACT generation
    instead of loading garbage.
    """

    def __init__(self, directory, prefix="ckpt", max_keep=3):
        self.directory = directory
        self.prefix = prefix
        self.max_keep = max_keep
        os.makedirs(directory, exist_ok=True)

    # -- paths ---------------------------------------------------------
    def _manifest_path(self):
        return os.path.join(self.directory, f"{self.prefix}.manifest.json")

    def _params_path(self, step):
        return os.path.join(self.directory,
                            f"{self.prefix}-{step:08d}.params")

    def _states_path(self, step):
        return os.path.join(self.directory,
                            f"{self.prefix}-{step:08d}.states")

    def _read_manifest(self):
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"steps": []}

    def _write_atomic(self, path, writer):
        fd, tmp = tempfile.mkstemp(dir=self.directory,
                                   prefix=os.path.basename(path) + ".tmp")
        os.close(fd)
        try:
            writer(tmp)
            # flush DATA before the rename: a journaled rename without a
            # data fsync can survive power loss pointing at torn content
            fd2 = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd2)
            finally:
                os.close(fd2)
            os.replace(tmp, path)  # atomic on POSIX
            dirfd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    # -- commit (shared by the sync and write-behind paths) ------------
    def _commit(self, step, params_host, states, extra, data_state):
        """Write one generation to disk + manifest. `params_host` is a
        {name: host array} mapping; `states` is the optimizer-state blob
        (or None). Runs on the caller thread (sync save) or the writer
        thread (save_async)."""
        from .ndarray import utils as _ndu
        from .ndarray.ndarray import NDArray
        t0 = time.perf_counter()
        inject("ckpt_write")
        step = int(step)
        ppath = self._params_path(step)
        # serialize once and hash the in-memory payload: the manifest
        # digest costs no write-then-read-back round trip
        payload = _ndu.save_bytes(
            {k: NDArray(v) for k, v in params_host.items()})

        def write_blob(blob):
            def write(tmp):
                with open(tmp, "wb") as f:
                    f.write(blob)
            return write

        self._write_atomic(ppath, write_blob(payload))
        entry = {"step": step, "has_states": states is not None,
                 "time": time.time(),
                 "sha256": {"params": hashlib.sha256(payload).hexdigest()},
                 "bytes": {"params": len(payload)}}
        if states is not None:
            spath = self._states_path(step)
            self._write_atomic(spath, write_blob(states))
            entry["sha256"]["states"] = hashlib.sha256(states).hexdigest()
            entry["bytes"]["states"] = len(states)
        if extra:
            entry["extra"] = extra
        if data_state is not None:
            entry["data_state"] = data_state
        man = self._read_manifest()
        man["steps"] = [e for e in man["steps"] if e["step"] != step]
        man["steps"].append(entry)
        man["steps"].sort(key=lambda e: e["step"])
        while len(man["steps"]) > self.max_keep:
            old = man["steps"].pop(0)
            for p in (self._params_path(old["step"]),
                      self._states_path(old["step"])):
                if os.path.exists(p):
                    os.remove(p)

        def write_manifest(tmp):
            with open(tmp, "w") as f:
                f.write(json.dumps(man, indent=1))

        self._write_atomic(self._manifest_path(), write_manifest)
        _bump("ckpt_saves")
        _bump("ckpt_write_ms", (time.perf_counter() - t0) * 1e3)
        with _stats_lock:
            _counters["ckpt_last_step"] = max(_counters["ckpt_last_step"],
                                              step)
        return ppath

    @staticmethod
    def _trainer_states(trainer):
        if trainer is None:
            return None
        return trainer.states_bytes()

    # -- API -----------------------------------------------------------
    def save(self, step, net=None, trainer=None, extra=None,
             data_state=None, params=None):
        """Checkpoint at `step` synchronously. Either a gluon `net` (and
        optional `trainer`) or a raw {name: array} `params` mapping (the
        TrainStep pytree path). `data_state` is an opaque JSON dict — the
        data-iterator cursor — restored via :meth:`data_state`. Returns
        the params path."""
        if (net is None) == (params is None):
            raise MXNetError("save needs exactly one of net= or params=")
        host = _snapshot_params(net) if net is not None else {
            k: _as_host(v) for k, v in params.items()}
        return self._commit(step, host, self._trainer_states(trainer),
                            extra, data_state)

    def _verify(self, entry):
        """None when the generation's artifacts are intact on disk, else a
        reason string. Legacy entries without checksums fall back to an
        existence check."""
        step = entry["step"]
        sha = entry.get("sha256", {})
        sizes = entry.get("bytes", {})
        paths = {"params": self._params_path(step)}
        if entry.get("has_states"):
            paths["states"] = self._states_path(step)
        for kind, path in paths.items():
            if not os.path.exists(path):
                return f"{kind} file missing"
            if kind in sizes and os.path.getsize(path) != sizes[kind]:
                return (f"{kind} file is {os.path.getsize(path)} bytes, "
                        f"manifest says {sizes[kind]}")
            if kind in sha and _digest(path) != sha[kind]:
                return f"{kind} sha256 mismatch (bit rot or torn write)"
        return None

    def _intact_entries(self):
        """Manifest entries newest-first, each verified on disk; corrupt
        generations are skipped (counted + logged) — degradation, not a
        crash."""
        out = []
        for e in reversed(self._read_manifest()["steps"]):
            reason = self._verify(e)
            if reason is None:
                out.append(e)
            else:
                _bump("ckpt_fallbacks")
                _log.warning(
                    "checkpoint step %d unusable (%s); falling back to an "
                    "older generation", e["step"], reason)
        return out

    def latest_step(self):
        """Newest step whose artifacts verify on disk, or None."""
        entries = self._intact_entries()
        return entries[0]["step"] if entries else None

    def restore(self, net, trainer=None, step=None, ctx=None):
        """Load params (+trainer states) from `step` (default: newest
        INTACT generation). With step=None, a corrupt or partially-missing
        newest generation degrades to the next older intact one (counted
        in ``ckpt_fallbacks``); an explicitly requested step is loaded
        as-asked or raises. Returns the restored step number."""
        if step is not None:
            entries = [e for e in self._read_manifest()["steps"]
                       if e["step"] == step]
            if not entries:
                raise MXNetError(f"no checkpoint for step {step} in "
                                 f"{self.directory}")
            reason = self._verify(entries[0])
            if reason is not None:
                raise MXNetError(
                    f"checkpoint step {step} unusable: {reason}")
        else:
            entries = self._intact_entries()
            if not entries:
                raise MXNetError(f"no checkpoint found in {self.directory}")
        last_err = None
        for e in entries:
            try:
                self._load_entry(e, net, trainer, ctx)
                return e["step"]
            except (MXNetError, OSError, ValueError) as err:
                # container-level corruption the checksum pass could not
                # see (legacy manifest without sha256): degrade further
                last_err = err
                _bump("ckpt_fallbacks")
                _log.warning("checkpoint step %d failed to load (%s); "
                             "falling back", e["step"], err)
        raise MXNetError(f"no restorable checkpoint in {self.directory}: "
                         f"{last_err}")

    def _load_entry(self, entry, net, trainer, ctx):
        step = entry["step"]
        net.load_parameters(self._params_path(step), ctx=ctx)
        if trainer is not None:
            spath = self._states_path(step)
            if os.path.exists(spath):
                trainer.load_states(spath)
            elif entry.get("has_states"):
                raise MXNetError(
                    f"checkpoint step {step} was saved with trainer state "
                    f"but {spath} is missing; refusing a silent partial "
                    "resume (pass trainer=None to load params only)")

    def restore_arrays(self, step=None):
        """Raw-pytree restore (the TrainStep path): returns
        ``(step, {name: NDArray}, data_state)`` from `step` (default:
        newest intact generation), with the same corruption fallback as
        :meth:`restore`."""
        from .ndarray import utils as _ndu
        if step is not None:
            entries = [e for e in self._read_manifest()["steps"]
                       if e["step"] == step]
        else:
            entries = self._intact_entries()
        if not entries:
            raise MXNetError(f"no checkpoint found in {self.directory}")
        last_err = None
        for e in entries:
            try:
                arrays = _ndu.load(self._params_path(e["step"]))
                return e["step"], arrays, e.get("data_state")
            except (MXNetError, OSError, ValueError) as err:
                last_err = err
                _bump("ckpt_fallbacks")
                _log.warning("checkpoint step %d failed to load (%s); "
                             "falling back", e["step"], err)
        raise MXNetError(f"no restorable checkpoint in {self.directory}: "
                         f"{last_err}")

    def extra(self, step=None):
        """The `extra` dict saved with a step (default: newest intact)."""
        if step is None:
            step = self.latest_step()
        for e in self._read_manifest()["steps"]:
            if e["step"] == step:
                return e.get("extra", {})
        return {}

    def data_state(self, step=None):
        """The data-iterator cursor saved with a step (default: newest
        intact), or None — the mid-epoch-exact resume position."""
        if step is None:
            step = self.latest_step()
        for e in self._read_manifest()["steps"]:
            if e["step"] == step:
                return e.get("data_state")
        return None


def _as_host(v):
    import numpy as _np
    data = getattr(v, "_data", v)
    if hasattr(data, "devices"):
        import jax
        data = jax.device_get(data)
    return _np.asarray(data)


class AsyncCheckpointManager(CheckpointManager):
    """Write-behind checkpointing: ``save_async`` snapshots to host memory
    and returns; a single background writer thread pays the fsync'd disk
    write. The step-blocking cost is ONE device->host copy of the params.

    Queue policy: bounded at ``MXNET_CKPT_QUEUE`` (default 2) pending
    snapshots; when full the OLDEST pending snapshot is dropped (counted
    in ``ckpt_dropped``) — the newest state is always the one that lands.
    A write failure is logged + counted (``ckpt_errors``) and re-raised at
    the next ``flush()``; the train loop itself never stalls or dies on a
    sick disk.
    """

    def __init__(self, directory, prefix="ckpt", max_keep=3,
                 queue_size=None):
        super().__init__(directory, prefix=prefix, max_keep=max_keep)
        if queue_size is None:
            from .util import getenv_int
            queue_size = getenv_int("MXNET_CKPT_QUEUE")
        self.queue_size = max(1, int(queue_size))
        self._wlock = _mxsan.lock(
            "fault.py", "self._wlock")      # guards _pending/_busy/_error
        self._pending = deque()
        self._work = threading.Event()      # snapshot queued
        self._settled = threading.Event()   # queue empty AND writer idle
        self._settled.set()
        self._stopping = False
        self._error = None
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="mxtpu-ckpt-writer",
                                        daemon=True)
        self._writer.start()

    # -- producer (the train loop) -------------------------------------
    def save_async(self, step, net=None, trainer=None, extra=None,
                   data_state=None, params=None):
        """Enqueue a checkpoint of `step`; returns immediately after the
        host snapshot. Accepts the same net/params forms as ``save``."""
        if (net is None) == (params is None):
            raise MXNetError("save_async needs exactly one of net= or "
                             "params=")
        host = _snapshot_params(net) if net is not None else {
            k: _as_host(v) for k, v in params.items()}
        states = self._trainer_states(trainer)
        snap = (int(step), host, states, extra, data_state)
        with self._wlock:
            if self._stopping:
                raise MXNetError("AsyncCheckpointManager is closed")
            while len(self._pending) >= self.queue_size:
                dropped = self._pending.popleft()
                _bump("ckpt_dropped")
                _log.warning(
                    "checkpoint queue full: dropping pending snapshot of "
                    "step %d (slow disk?)", dropped[0])
            self._pending.append(snap)
            self._settled.clear()
        _bump("ckpt_async_snapshots")
        self._work.set()

    # -- writer thread -------------------------------------------------
    def _writer_loop(self):
        try:
            # Linux nice is per-task: who=0 from inside the thread demotes
            # only the writer, so it yields CPU to the compute threads.
            # nice 10 (not 19) keeps enough share to drain the queue even
            # on a fully loaded single-core host.
            os.setpriority(os.PRIO_PROCESS, 0, 10)
        except (AttributeError, OSError):
            pass                        # non-Linux or not permitted
        while True:
            self._work.wait()
            with self._wlock:
                if not self._pending:
                    self._work.clear()
                    if self._stopping:
                        self._settled.set()
                        return
                    self._settled.set()
                    continue
                snap = self._pending.popleft()
            step, host, states, extra, data_state = snap
            try:
                self._commit(step, host, states, extra, data_state)
            except Exception as e:      # noqa: BLE001 — surfaced at flush
                _bump("ckpt_errors")
                with self._wlock:
                    self._error = e
                _log.warning("background checkpoint of step %d failed:\n%s",
                             step, traceback.format_exc())

    # -- lifecycle -----------------------------------------------------
    def flush(self, timeout=None):
        """Block until every queued snapshot is on disk; raise the first
        background write error (cleared once raised)."""
        if not self._settled.wait(timeout):
            raise MXNetError(
                f"checkpoint writer did not settle within {timeout}s")
        with self._wlock:
            err, self._error = self._error, None
        if err is not None:
            raise MXNetError(f"background checkpoint write failed: {err!r}")

    def pending(self):
        """Snapshots queued but not yet on disk (bench/telemetry)."""
        with self._wlock:
            return len(self._pending)

    def close(self, timeout=30):
        """Drain the queue and stop the writer. Safe to call twice."""
        with self._wlock:
            if self._stopping:
                return
            self._stopping = True
        self._work.set()                # wake the writer to observe stop
        self._writer.join(timeout)
        with self._wlock:
            err, self._error = self._error, None
        if err is not None:
            raise MXNetError(f"background checkpoint write failed: {err!r}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        try:
            self.close()
        except MXNetError:
            if exc == (None, None, None):
                raise
            # an exception is already propagating; don't mask it

    def __del__(self):
        try:
            self.close(timeout=5)
        except Exception:               # noqa: BLE001 — interpreter
            pass                        # shutdown: thread/queue gone


def resume_or_start(manager, net, trainer=None, ctx=None):
    """Restore the latest intact checkpoint if one exists; returns the
    step to resume from (0 when starting fresh)."""
    step = manager.latest_step()
    if step is None:
        return 0
    manager.restore(net, trainer, step=step, ctx=ctx)
    return step


class PreemptionHandler:
    """SIGTERM/SIGINT-driven graceful stop.

    The signal handler ONLY sets a flag — checkpointing from inside a
    signal handler could capture parameters mid-update. `on_preempt` is
    deferred to the first `should_stop()` call after the signal, i.e. the
    training loop's step boundary, where state is consistent.

    usage:
        with PreemptionHandler() as pre:
            for step in range(start, total):
                ...train one step...
                if pre.should_stop():
                    mgr.save(step, net, trainer)
                    break
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_preempt=None):
        self._signals = tuple(signals)
        self._on_preempt = on_preempt
        self._stop = threading.Event()
        self._callback_fired = False
        self._prev = {}
        self._installed = False

    def _handler(self, signum, frame):
        self._stop.set()

    def install(self):
        if self._installed:
            return self
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        self._installed = True
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def should_stop(self):
        stopped = self._stop.is_set()
        if stopped and self._on_preempt is not None and \
                not self._callback_fired:
            # deferred to here: main-thread, step-boundary context
            self._callback_fired = True
            try:
                self._on_preempt()
            except Exception:   # never mask the shutdown path — but a
                #                 failed EMERGENCY CHECKPOINT must not be
                #                 silent either: the operator reading the
                #                 logs decides whether the run is resumable
                _log.warning(
                    "PreemptionHandler on_preempt callback failed — the "
                    "emergency checkpoint may be missing or stale:\n%s",
                    traceback.format_exc())
        return stopped

    def reset(self):
        self._stop.clear()
        self._callback_fired = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()


# ---------------------------------------------------------------------------
# liveness (reference kvstore_dist.h:121 get_dead_nodes)
# ---------------------------------------------------------------------------

_live_kvstores = []     # weakrefs to distributed KVStores, newest last


def _register_kvstore(kv):
    """Called by kvstore.KVStore for stores with a liveness registry so
    the module-level get_dead_nodes answers for the current job."""
    _live_kvstores.append(weakref.ref(kv))
    del _live_kvstores[:-8]     # bound growth across many test stores


def get_dead_nodes(timeout_sec=None):
    """Ranks considered dead by the newest registered distributed KVStore
    (reference kvstore_dist.h:121 get_dead_nodes): the dist_async server's
    heartbeat registry, or the coordination-service generation watch in
    dist_sync. With no distributed store in the process there is no
    partial-failure mode to report: returns []."""
    if timeout_sec is None:
        from .util import getenv_int
        timeout_sec = getenv_int("MXNET_DEAD_NODE_TIMEOUT")
    for ref in reversed(_live_kvstores):
        kv = ref()
        if kv is None:
            continue
        try:
            return kv.get_dead_nodes(timeout=timeout_sec)
        except Exception as e:      # noqa: BLE001 — a torn-down store must
            _log.warning("get_dead_nodes via %r failed: %s", kv, e)
            continue                # not mask a live one registered earlier
    return []
