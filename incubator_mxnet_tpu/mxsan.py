"""Witness-based concurrency sanitizer (runtime half of the lock rules).

``tools/mxlint/lock_order.py`` *declares* the lock hierarchy and CC02
enforces it lexically, but nothing checked what threads actually do at
runtime — exactly the gap that produced PR 3's GC self-deadlock and
PR 6's first-call latch race.  This module closes it with the classic
witness algorithm (the FreeBSD ``WITNESS(4)`` / TSan lock-order idea):
every instrumented lock acquisition made while another instrumented
lock is held records an *edge* ``held_top -> acquired`` with the
acquiring thread's trimmed stack.  A cycle in the observed edge graph
is an AB/BA deadlock that merely hasn't hung yet — the sanitizer
reports it from the orderings alone, no hang required.

Instrumentation is a thin factory shim: modules create their locks via
``mxsan.lock("serve/decode.py", "self._lock")`` instead of
``threading.Lock()``.  Gate discipline (the PR-10/11 cached-bool
idiom): with ``MXNET_MXSAN`` off the factories return the *raw stdlib
primitives* — not a pass-through wrapper, the very same object type a
build without this module would create — and ``record_count()`` stays
exactly 0 (tests assert the counter, not wall-clock deltas).  Gate on,
they return ``_SanLock`` wrappers that maintain a per-thread held
stack, record first-seen edges / re-entry on non-reentrant locks into
bounded tables plus a chronological event ring (``MXNET_MXSAN_RING``),
and run an incremental cycle check on each new edge.  Blocking-call
interceptors (``time.sleep``, un-timed ``Thread.join``, un-timed
``queue.Queue.get``, ``subprocess.Popen``, socket connect/accept/
send/recv) additionally flag lock-held-across-blocking-call, and
``threading.Thread.start`` is shadowed so unnamed or leaked non-daemon
threads surface at drain.

``witness()`` snapshots everything as a plain-JSON dict;
``dump(path)`` (or ``MXNET_MXSAN_LOG`` at interpreter exit) writes it
for offline replay via ``python -m tools.mxsan``, whose analyzer
cross-checks every observed edge against ``lock_order.py`` — an
observed nesting absent from the declarations is a finding, which is
what makes the registry *proven* rather than aspirational.

Lock hierarchy: the module ``_lock`` is a LEAF guarding the event
ring, edge/blocking/re-entry tables, and counters; no instrumented
code, I/O, or other-module call ever runs under it.  It is a raw
stdlib lock on purpose (the sanitizer cannot instrument itself).

See ``docs/architecture/note_static_analysis.md`` (runtime-sanitizer
chapter).
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import threading
import time
import traceback
import weakref

from .util import getenv_bool, getenv_int, getenv_str

__all__ = [
    "enabled", "enable", "reset", "record_count", "clear", "stats",
    "lock", "rlock", "condition",
    "edges", "events", "witness", "dump", "thread_findings",
    "render_prometheus",
]

_lock = threading.Lock()        # LEAF: ring + tables + counters only
_tls = threading.local()        # .held = list of _SanLock this thread holds

_enabled = None                 # cached MXNET_MXSAN bool (None = unread)
_records = 0                    # observations booked; exactly 0 while off
_acquires = 0                   # instrumented acquisitions (diagnostics)
_dropped = 0                    # ring evictions
_events = None                  # deque ring of chronological observations
_edges = None                   # (a, b) -> {count, thread, stack}
_adj = None                     # a -> set(b), the observed-order digraph
_blocking = None                # (kind, innermost_site) -> {count, ...}
_reentry = None                 # site -> {count, thread, stack}
_cycles = None                  # list of deduped cycle reports
_cycle_keys = None              # frozenset(edge pairs) already reported
_threads = None                 # deque of (name, daemon, weakref) started
_installed = False              # blocking/thread interceptors in place
_atexit_done = False            # MXNET_MXSAN_LOG dump hook registered
_orig = {}                      # saved originals for _uninstall
_sock_added = []                # socket.socket attrs we ADDED (vs replaced)

_STACK_DEPTH = 6                # trimmed frames kept per observation
_THREAD_CAP = 512               # started-thread table bound
# Thread names outside our control (pool workers, harness plumbing):
# exempt from the mxtpu-* naming rule, still subject to nothing else.
_THREAD_EXEMPT = ("ThreadPoolExecutor", "Dummy-", "pytest", "asyncio",
                  "pydevd", "paramiko")
# socketserver/ThreadingHTTPServer spawn their own per-connection
# threads internally; their targets, not their names, identify them.
_THREAD_EXEMPT_SUBSTR = ("(process_request_thread)", "(serve_forever)")


# ---------------------------------------------------------------------------
# gate (cached bool, force-override for tests, reset forgets everything)
# ---------------------------------------------------------------------------

def enabled():
    """Cached ``MXNET_MXSAN`` gate — the env var is read once."""
    global _enabled
    if _enabled is None:
        _enabled = getenv_bool("MXNET_MXSAN")
        if _enabled:
            _install()
    return _enabled


def enable(on=True):
    """Force the gate (tests / diagnose probes). Returns the previous
    cached value (None if the env var had not been consulted yet)."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    if _enabled:
        _install()
    else:
        _uninstall()
    return prev


def reset():
    """Forget the cached gate, restore every intercepted callable, and
    drop all witness state."""
    global _enabled
    _uninstall()
    with _lock:
        _enabled = None
        _clear_locked(stats=True)


def record_count():
    """Total sanitizer observations booked (edge sightings, blocking
    calls under a lock, re-entries, cycles). Exactly 0 while the gate
    is off — the zero-overhead assert counts records, it does not time
    anything."""
    with _lock:
        return _records


def clear(stats=False):
    """Drop the ring and witness tables; with ``stats=True`` also zero
    the counters (mirrors ``shardlint.clear``)."""
    with _lock:
        _clear_locked(stats=stats)


def _clear_locked(stats=False):
    global _records, _acquires, _dropped
    global _events, _edges, _adj, _blocking, _reentry
    global _cycles, _cycle_keys, _threads
    _events = None
    _edges = None
    _adj = None
    _blocking = None
    _reentry = None
    _cycles = None
    _cycle_keys = None
    _threads = None
    if stats:
        _records = 0
        _acquires = 0
        _dropped = 0


def stats():
    """Plain picklable counter snapshot (all-zero while the gate is
    off; asserted by the zero-overhead tests)."""
    with _lock:
        return {
            "enabled": bool(_enabled),
            "records": _records,
            "acquires": _acquires,
            "dropped": _dropped,
            "edges": len(_edges) if _edges else 0,
            "blocking": sum(b["count"] for b in _blocking.values())
            if _blocking else 0,
            "reentries": sum(r["count"] for r in _reentry.values())
            if _reentry else 0,
            "cycles": len(_cycles) if _cycles else 0,
            "threads": len(_threads) if _threads else 0,
        }


# ---------------------------------------------------------------------------
# recording internals (every helper here runs with _lock held briefly
# and never calls out of the module)
# ---------------------------------------------------------------------------

def _stack():
    """Trimmed acquisition stack: repo-relative ``file:line:func`` rows,
    innermost last, mxsan's own frames dropped."""
    rows = []
    for fr in traceback.extract_stack():
        fn = fr.filename.replace(os.sep, "/")
        if fn.endswith("incubator_mxnet_tpu/mxsan.py"):
            continue
        for mark in ("incubator_mxnet_tpu/", "tools/", "tests/"):
            i = fn.rfind(mark)
            if i >= 0:
                fn = fn[i:]
                break
        else:
            fn = fn.rsplit("/", 1)[-1]
        rows.append("%s:%d:%s" % (fn, fr.lineno, fr.name))
    return rows[-_STACK_DEPTH:]


def _push_event(ev):
    """Append to the bounded ring (drop-oldest, counted) and bump the
    record counter. Caller holds _lock."""
    global _events, _records, _dropped
    if _events is None:
        _events = collections.deque(
            maxlen=max(64, getenv_int("MXNET_MXSAN_RING")))
    if len(_events) == _events.maxlen:
        _dropped += 1
    _events.append(ev)
    _records += 1


def _held():
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _note_edge(a, b, thread_name):
    """First sighting of a->b books an edge (and runs the incremental
    cycle check); repeats just bump its count."""
    global _edges, _adj
    stack = _stack()
    with _lock:
        if _edges is None:
            _edges = {}
            _adj = {}
        row = _edges.get((a, b))
        if row is not None:
            row["count"] += 1
            _records_bump()
            return
        _edges[(a, b)] = {"count": 1, "thread": thread_name, "stack": stack}
        _adj.setdefault(a, set()).add(b)
        _push_event({"type": "edge", "a": a, "b": b,
                     "thread": thread_name, "stack": stack})
        _check_cycle_locked(a, b, thread_name)


def _records_bump():
    global _records
    _records += 1


def _check_cycle_locked(a, b, thread_name):
    """New edge a->b closed a cycle iff b already reaches a. BFS over
    the small site digraph; dedup by the cycle's edge set."""
    global _cycles, _cycle_keys
    path = _find_path_locked(b, a)
    if path is None:
        return
    full = (a,) + path              # a -> b -> ... -> a
    pairs = tuple(zip(full, full[1:]))
    key = frozenset(pairs)
    if _cycle_keys is None:
        _cycle_keys = set()
        _cycles = []
    if key in _cycle_keys:
        return
    _cycle_keys.add(key)
    stacks = {}
    for pa, pb in pairs:
        row = _edges.get((pa, pb))
        stacks["%s -> %s" % (pa, pb)] = {
            "thread": row["thread"] if row else "?",
            "stack": row["stack"] if row else [],
        }
    cyc = {"path": list(full), "edges": [list(p) for p in pairs],
           "stacks": stacks, "thread": thread_name}
    _cycles.append(cyc)
    _push_event(dict(cyc, type="cycle"))


def _find_path_locked(src, dst):
    if _adj is None:
        return None
    q = collections.deque([(src, (src,))])
    seen = {src}
    while q:
        node, path = q.popleft()
        if node == dst:
            return path
        for nxt in _adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                q.append((nxt, path + (nxt,)))
    return None


def _note_reentry(site, thread_name):
    global _reentry
    stack = _stack()
    with _lock:
        if _reentry is None:
            _reentry = {}
        row = _reentry.get(site)
        if row is not None:
            row["count"] += 1
            _records_bump()
            return
        _reentry[site] = {"count": 1, "thread": thread_name, "stack": stack}
        _push_event({"type": "reentry", "site": site,
                     "thread": thread_name, "stack": stack})


def _note_blocking(kind):
    """A known-blocking call ran on a thread holding >=1 instrumented
    lock. Never raises — this sits inside intercepted stdlib calls."""
    try:
        if not _enabled:
            return
        held = getattr(_tls, "held", None)
        if not held:
            return
        global _blocking
        site = held[-1].site
        held_sites = [h.site for h in held]
        thread_name = threading.current_thread().name
        stack = _stack()
        with _lock:
            if _blocking is None:
                _blocking = {}
            row = _blocking.get((kind, site))
            if row is not None:
                row["count"] += 1
                _records_bump()
                return
            _blocking[(kind, site)] = {
                "count": 1, "held": held_sites,
                "thread": thread_name, "stack": stack,
            }
            _push_event({"type": "blocking", "kind": kind, "site": site,
                         "held": held_sites, "thread": thread_name,
                         "stack": stack})
    except Exception:
        pass


# ---------------------------------------------------------------------------
# the instrumented lock
# ---------------------------------------------------------------------------

class _SanLock:
    """Wrapper around one stdlib lock: forwards acquire/release and
    books held-stack + edge/re-entry observations. Only ever handed
    out while the gate is ON."""

    __slots__ = ("site", "_inner", "_reentrant", "__weakref__")

    def __init__(self, site, inner, reentrant):
        self.site = site
        self._inner = inner
        self._reentrant = reentrant

    def acquire(self, blocking=True, timeout=-1):
        held = _held()
        thread_name = threading.current_thread().name
        already = any(h is self for h in held)
        if already and not self._reentrant:
            # Would self-deadlock; report BEFORE blocking on it so the
            # witness survives even if the caller then hangs.
            _note_reentry(self.site, thread_name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            global _acquires
            if held and not already:
                _note_edge(held[-1].site, self.site, thread_name)
            with _lock:
                _acquires += 1
            held.append(self)
        return got

    def release(self):
        held = getattr(_tls, "held", None)
        if held:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _is_owned(self):
        # threading.Condition needs this for RLock-backed waits; fall
        # back to the held-stack for plain locks.
        inner = getattr(self._inner, "_is_owned", None)
        if inner is not None:
            return inner()
        return any(h is self for h in getattr(_tls, "held", ()))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.release()

    def __repr__(self):
        return "<mxsan %s of %r>" % (self.site, self._inner)


def lock(module, name):
    """A ``threading.Lock`` for acquisition site ``module:name`` (the
    lock_order.py spellings, e.g. ``lock("serve/decode.py",
    "self._lock")``). Gate off: the raw stdlib object."""
    if not enabled():
        return threading.Lock()
    return _SanLock("%s:%s" % (module, name), threading.Lock(), False)


def rlock(module, name):
    """A ``threading.RLock`` for site ``module:name`` (re-entry on it
    is legal and never reported)."""
    if not enabled():
        return threading.RLock()
    return _SanLock("%s:%s" % (module, name), threading.RLock(), True)


def condition(module, name, lock=None):
    """A ``threading.Condition``. An explicit ``lock`` (instrumented or
    not) is passed through; otherwise the underlying RLock is created
    via :func:`rlock` so waits/notifies book edges too."""
    if not enabled():
        return threading.Condition(lock)
    if lock is None:
        lock = rlock(module, name)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# blocking-call + thread-lifecycle interceptors
# ---------------------------------------------------------------------------

def _install():
    """Shadow the known-blocking stdlib calls and Thread.start. Installed
    when the gate turns on; every original is restored by _uninstall."""
    global _installed, _atexit_done
    if _installed:
        return
    _installed = True
    import queue as _queue
    import socket as _socket
    import subprocess as _subprocess

    _orig["time.sleep"] = time.sleep

    def _sleep(secs):
        _note_blocking("time.sleep")
        return _orig["time.sleep"](secs)
    time.sleep = _sleep

    _orig["Thread.join"] = threading.Thread.join

    def _join(self, timeout=None):
        if timeout is None:
            _note_blocking("Thread.join")
        return _orig["Thread.join"](self, timeout)
    threading.Thread.join = _join

    _orig["Thread.start"] = threading.Thread.start

    def _start(self):
        _note_thread(self)
        return _orig["Thread.start"](self)
    threading.Thread.start = _start

    _orig["Queue.get"] = _queue.Queue.get

    def _get(self, block=True, timeout=None):
        if block and timeout is None:
            _note_blocking("queue.get")
        return _orig["Queue.get"](self, block, timeout)
    _queue.Queue.get = _get

    _orig["Popen.__init__"] = _subprocess.Popen.__init__

    def _popen(self, *a, **kw):
        _note_blocking("subprocess.Popen")
        return _orig["Popen.__init__"](self, *a, **kw)
    _subprocess.Popen.__init__ = _popen

    del _sock_added[:]
    for meth in ("connect", "accept", "recv", "send", "sendall"):
        real = getattr(_socket.socket, meth)
        if meth in vars(_socket.socket):
            _orig["socket." + meth] = real
        else:
            _sock_added.append(meth)   # inherited from C base: delattr later

        def _make(meth=meth, real=real):
            def _wrapped(self, *a, **kw):
                _note_blocking("socket." + meth)
                return real(self, *a, **kw)
            _wrapped.__name__ = meth
            return _wrapped
        setattr(_socket.socket, meth, _make())

    if not _atexit_done:
        _atexit_done = True
        atexit.register(_atexit_dump)


def _uninstall():
    global _installed
    if not _installed:
        return
    _installed = False
    import queue as _queue
    import socket as _socket
    import subprocess as _subprocess
    time.sleep = _orig.pop("time.sleep")
    threading.Thread.join = _orig.pop("Thread.join")
    threading.Thread.start = _orig.pop("Thread.start")
    _queue.Queue.get = _orig.pop("Queue.get")
    _subprocess.Popen.__init__ = _orig.pop("Popen.__init__")
    for meth in _sock_added:
        try:
            delattr(_socket.socket, meth)
        except AttributeError:
            pass
    del _sock_added[:]
    for key in [k for k in _orig if k.startswith("socket.")]:
        setattr(_socket.socket, key.split(".", 1)[1], _orig.pop(key))


def _note_thread(t):
    """Book a started thread for the drain-time lifecycle audit. Never
    raises."""
    try:
        if not _enabled:
            return
        global _threads
        with _lock:
            if _threads is None:
                _threads = collections.deque(maxlen=_THREAD_CAP)
            _threads.append((t.name, bool(t.daemon), weakref.ref(t)))
    except Exception:
        pass


def thread_findings():
    """Drain-time audit of threads started while the gate was on:
    rows with a non-``mxtpu-*`` name ("unnamed") and/or still-alive
    non-daemon threads ("leaked"). Empty list when clean."""
    with _lock:
        rows = list(_threads) if _threads else []
    out = []
    for name, daemon, ref in rows:
        name = name or ""
        if name.startswith(_THREAD_EXEMPT) or \
                any(s in name for s in _THREAD_EXEMPT_SUBSTR):
            continue
        t = ref()
        alive = bool(t is not None and t.is_alive())
        problems = []
        if not name.startswith("mxtpu-"):
            problems.append("unnamed")
        if alive and not daemon:
            problems.append("leaked")
        if problems:
            out.append({"name": name, "daemon": daemon, "alive": alive,
                        "problems": problems})
    return out


# ---------------------------------------------------------------------------
# snapshots, witness log, telemetry
# ---------------------------------------------------------------------------

def edges():
    """Observed-edge table as {"a -> b": count} (diagnose probe)."""
    with _lock:
        if not _edges:
            return {}
        return {"%s -> %s" % k: v["count"] for k, v in _edges.items()}


def events():
    """Chronological ring snapshot (oldest first)."""
    with _lock:
        return list(_events) if _events else []


def witness():
    """The full witness snapshot as a plain-JSON dict — the same shape
    ``python -m tools.mxsan`` replays from disk."""
    threads = thread_findings()
    with _lock:
        return {
            "version": 1,
            "stats": {
                "enabled": bool(_enabled),
                "records": _records,
                "acquires": _acquires,
                "dropped": _dropped,
            },
            "edges": [
                {"a": a, "b": b, "count": row["count"],
                 "thread": row["thread"], "stack": row["stack"]}
                for (a, b), row in (_edges or {}).items()
            ],
            "blocking": [
                {"kind": kind, "site": site, "count": row["count"],
                 "held": row["held"], "thread": row["thread"],
                 "stack": row["stack"]}
                for (kind, site), row in (_blocking or {}).items()
            ],
            "reentry": [
                {"site": site, "count": row["count"],
                 "thread": row["thread"], "stack": row["stack"]}
                for site, row in (_reentry or {}).items()
            ],
            "cycles": list(_cycles or []),
            "threads": threads,
            "events": list(_events or []),
        }


def dump(path=None):
    """Write the witness log as JSON. ``path`` defaults to
    ``MXNET_MXSAN_LOG``; returns the path written or None."""
    path = path or getenv_str("MXNET_MXSAN_LOG")
    if not path:
        return None
    snap = witness()
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def _atexit_dump():
    try:
        if _enabled and record_count():
            dump()
    except Exception:
        pass


_PROM_FAMILIES = (
    ("records", "counter", "Sanitizer observations booked."),
    ("acquires", "counter", "Instrumented lock acquisitions."),
    ("edges", "gauge", "Distinct observed lock-order edges."),
    ("blocking", "counter", "Blocking calls made while holding a lock."),
    ("reentries", "counter", "Re-entry attempts on non-reentrant locks."),
    ("cycles", "gauge", "Distinct lock-order cycles observed."),
    ("dropped", "counter", "Witness ring evictions."),
)


def render_prometheus(labels=""):
    """``mxnet_mxsan_*`` exposition block; empty string until the first
    record so a gate-off scrape is byte-identical."""
    snap = stats()
    if not snap["records"]:
        return ""
    lab = "{%s}" % labels if labels else ""
    out = []
    for stat, mtype, help_text in _PROM_FAMILIES:
        name = "mxnet_mxsan_" + stat
        if mtype == "counter":
            name += "_total"
        out.append("# HELP %s %s" % (name, help_text))
        out.append("# TYPE %s %s" % (name, mtype))
        out.append("%s%s %d" % (name, lab, snap[stat]))
    return "\n".join(out) + "\n"
