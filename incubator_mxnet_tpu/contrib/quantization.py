"""Post-training INT8 quantization driver.

Reference: python/mxnet/contrib/quantization.py (976 LoC) — `quantize_model`
rewrites FLOP-heavy nodes to quantized variants with quantize/dequantize
glue, calibrating activation ranges over sample data with `naive` (min/max)
or `entropy` (KL-divergence-optimal threshold) modes; the graph pass lives
in src/operator/quantization/quantize_graph_pass.cc.

TPU-native: the rewritten graph runs int8 matmul/conv on the MXU with int32
accumulation (ops/quantization_ops.py); calibration executes the fp32 graph
once per batch and records per-layer output statistics.
"""
from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError

__all__ = ["quantize_model", "quantize_graph", "_calibrate_quantized_sym"]

_QUANTIZABLE = {"FullyConnected", "Convolution"}


def _optimal_threshold_kl(arr, quantized_dtype="int8", num_bins=2048,
                          num_quantized_bins=128):
    """KL-divergence-optimal clipping threshold over the |x| histogram
    (the algorithm behind the reference's entropy mode, quantization.py
    _get_optimal_threshold; smoothing per the standard TensorRT-style
    calibration so sparse histograms don't collapse to tiny thresholds)."""
    arr = _np.asarray(arr, dtype=_np.float64).ravel()
    arr = arr[_np.isfinite(arr)]
    if arr.size == 0:
        return 1e-8
    mag = _np.abs(arr)
    amax = float(mag.max())
    if amax < 1e-12:
        return 1e-8
    hist, edges = _np.histogram(mag, bins=num_bins, range=(0.0, amax))
    hist = hist.astype(_np.float64)
    eps = 1e-10
    best_div, best_t = None, amax
    stride = max(1, num_bins // 512)
    for i in range(num_quantized_bins, num_bins + 1, stride):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()  # clip outliers into the last kept bin
        if p.sum() <= 0:
            continue
        # quantize kept bins into num_quantized_bins, expand back over the
        # nonzero support only
        factor = i / num_quantized_bins
        q = _np.zeros(i)
        for j in range(num_quantized_bins):
            lo = int(_np.floor(j * factor))
            hi = int(_np.ceil((j + 1) * factor)) if j < num_quantized_bins - 1 \
                else i
            seg = hist[lo:hi]
            nz = seg != 0
            n_nz = int(nz.sum())
            if n_nz:
                q[lo:hi][nz] = seg[nz].sum() / n_nz
        p_n = p / p.sum()
        q_sum = q.sum()
        if q_sum <= 0:
            continue
        q_n = q / q_sum
        mask = p_n > 0
        div = float(_np.sum(p_n[mask] *
                            _np.log(p_n[mask] / (q_n[mask] + eps))))
        if best_div is None or div < best_div:
            best_div, best_t = div, float(edges[i])
    return best_t


def quantize_graph(sym, excluded_sym_names=(), quantized_dtype="int8",
                   calib_ranges=None):
    """Rewrite FullyConnected/Convolution nodes to their int8 forms with
    quantize/dequantize glue (reference quantize_graph_pass.cc).

    calib_ranges: {node_name: (min, max)} activation ranges; when a node's
    range is missing its input is quantized with on-the-fly min/max."""
    from .. import symbol as S
    from ..symbol.symbol import _Node, _topo
    from ..ops import registry as _registry

    excluded = set(excluded_sym_names)
    calib_ranges = calib_ranges or {}

    order = _topo(sym._outputs)
    mapping = {}  # id(old_node) -> (new_node, out_idx_shift)

    def conv(entry):
        node, idx = entry
        return (mapping[id(node)][0], idx + mapping[id(node)][1]) \
            if id(node) in mapping else entry

    q_fc = _registry.get_op("_contrib_quantized_fully_connected")
    q_conv = _registry.get_op("_contrib_quantized_conv")
    q_op = _registry.get_op("_contrib_quantize_v2")
    dq_op = _registry.get_op("_contrib_dequantize")

    for node in order:
        if node.op is None or node.op.name not in _QUANTIZABLE or \
                node.name in excluded:
            continue
        new_inputs = []
        mins_maxs = []
        for (inp, oi), aname in zip(node.inputs, node.arg_names):
            src = conv((inp, oi))
            rng = calib_ranges.get(f"{node.name}_{aname}")
            attrs = {"out_type": quantized_dtype}
            if rng is not None:
                attrs["min_calib_range"] = float(rng[0])
                attrs["max_calib_range"] = float(rng[1])
            qnode = _Node(q_op, f"{node.name}_{aname}_quantize", attrs,
                          [src], arg_names=["data"])
            new_inputs.append(qnode)
            mins_maxs.append(qnode)
        # quantized op: data, weight, bias, then the six range scalars
        ins, argn = [], []
        for qn, aname in zip(new_inputs, node.arg_names):
            ins.append((qn, 0))
            argn.append(aname)
        for qn, aname in zip(mins_maxs, node.arg_names):
            ins.append((qn, 1))
            argn.append(f"{aname}_min")
            ins.append((qn, 2))
            argn.append(f"{aname}_max")
        qop = q_fc if node.op.name == "FullyConnected" else q_conv
        qnode = _Node(qop, f"quantized_{node.name}", dict(node.attrs),
                      ins, extra=dict(node.extra), arg_names=argn)
        # dequantize uses the analytic int32 full-scale range (exact);
        # calibrated output ranges would only matter for int8 op chaining
        dq = _Node(dq_op, f"{node.name}_dequantize", {},
                   [(qnode, 0), (qnode, 1), (qnode, 2)],
                   arg_names=["qdata", "min_range", "max_range"])
        mapping[id(node)] = (dq, 0)

    if not mapping:
        return sym
    new_outputs = [(e[0], e[1]) for e in
                   (_rebuild_mapped(sym._outputs, mapping))]
    return _propagate_int8(S.Symbol(new_outputs))


def _rebuild_mapped(outputs, mapping):
    """Rebuild a graph applying `mapping` {id(old) -> (new_node, shift)}
    EVERYWHERE — including inside the replacement nodes' own input
    subtrees (a replacement's inputs still reference original upstream
    nodes that may themselves be mapped)."""
    from ..symbol.symbol import _Node

    rebuilt = {}

    def rebuild(node):
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        target = mapping[id(node)][0] if id(node) in mapping else node
        if target.op is None:
            rebuilt[id(node)] = target
            return target
        new_ins = []
        for inp, oi in target.inputs:
            nb = rebuild(inp)
            if id(inp) in mapping:
                oi = oi + mapping[id(inp)][1]
            new_ins.append((nb, oi))
        nn = _Node(target.op, target.name, target.attrs, new_ins,
                   extra=target.extra, arg_names=target.arg_names)
        rebuilt[id(node)] = nn
        return nn

    return [(rebuild(n), i + (mapping[id(n)][1] if id(n) in mapping else 0))
            for n, i in outputs]


def _propagate_int8(sym):
    """Push dequantize nodes DOWN through range-preserving ops: a
    relu / max-pool / flatten / residual-add whose inputs all come from
    dequantize nodes is replaced by its quantized form consuming the int
    codes directly (reference: the quantize pass's avoid-dequantize
    patterns across quantized_pooling.cc, quantized_activation.cc,
    quantized_elemwise_add.cc). Repeats to a fixpoint so chains like
    conv -> relu -> pool stay integer end to end."""
    from .. import symbol as S
    from ..symbol.symbol import _Node, _topo
    from ..ops import registry as _registry

    dq_op = _registry.get_op("_contrib_dequantize")
    q_act = _registry.get_op("_contrib_quantized_act")
    q_pool = _registry.get_op("_contrib_quantized_pooling")
    q_flat = _registry.get_op("_contrib_quantized_flatten")
    q_add = _registry.get_op("_contrib_quantized_elemwise_add")
    q_v2 = _registry.get_op("_contrib_quantize_v2")
    req_op = _registry.get_op("_contrib_requantize")
    int32_producers = (_registry.get_op("_contrib_quantized_conv"),
                       _registry.get_op("_contrib_quantized_fully_connected"),
                       q_add)

    def is_dq(entry):
        node, oi = entry
        return node.op is dq_op and oi == 0

    def _traces_to_int32(node, passthrough, producers):
        """Code width of a quantized chain: walk the range-preserving ops
        (act/pool/flatten keep their input's dtype) back to the ultimate
        producer; int32 iff it is a conv/fc/add accumulator."""
        seen = 0
        while node.op in passthrough and seen < 64:
            node = node.inputs[0][0]
            seen += 1
        return node.op in producers

    for _ in range(32):          # fixpoint; each pass sinks one layer
        order = _topo(sym._outputs)
        mapping = {}

        def conv(entry):
            node, idx = entry
            return (mapping[id(node)][0], idx + mapping[id(node)][1]) \
                if id(node) in mapping else entry

        changed = False
        for node in order:
            if node.op is None or id(node) in mapping:
                continue
            ins = [conv(e) for e in node.inputs]
            name = node.op.name
            new = None
            if (name == "relu" or (name == "Activation" and
                                   node.attrs.get("act_type") == "relu")) \
                    and is_dq(ins[0]):
                q, lo, hi = ins[0][0].inputs
                new = _Node(q_act, f"quantized_{node.name}", {},
                            [q, lo, hi],
                            arg_names=["data", "min_range", "max_range"])
            elif name == "Pooling" and is_dq(ins[0]) and \
                    node.attrs.get("pool_type", "max") in ("max",):
                q, lo, hi = ins[0][0].inputs
                new = _Node(q_pool, f"quantized_{node.name}",
                            dict(node.attrs), [q, lo, hi],
                            arg_names=["data", "min_range", "max_range"])
            elif name in ("Flatten", "flatten") and is_dq(ins[0]):
                q, lo, hi = ins[0][0].inputs
                new = _Node(q_flat, f"quantized_{node.name}", {},
                            [q, lo, hi],
                            arg_names=["data", "min_range", "max_range"])
            elif name in ("elemwise_add", "broadcast_add", "_plus") and \
                    len(ins) == 2 and is_dq(ins[0]) and is_dq(ins[1]):
                lq, llo, lhi = ins[0][0].inputs
                rq, rlo, rhi = ins[1][0].inputs
                new = _Node(q_add, f"quantized_{node.name}", {},
                            [lq, rq, llo, lhi, rlo, rhi],
                            arg_names=["lhs", "rhs", "lhs_min", "lhs_max",
                                       "rhs_min", "rhs_max"])
            elif node.op is q_v2 and is_dq(ins[0]):
                # dequantize -> quantize_v2 between quantized consumers
                # is a round trip through fp32 (HBM-materialized + a
                # minmax pass). Collapse to ONE code-level bridge:
                # int32 accumulator chains take requantize (reference
                # requantize-inl.h), already-int8 chains take the
                # rescale_int8 range bridge (identity when calibration
                # gave producer and consumer the same range).
                from_int32 = _traces_to_int32(
                    ins[0][0].inputs[0][0], (q_act, q_pool, q_flat),
                    int32_producers)
                op2, prefix = ((req_op, "requantized") if from_int32 else
                               (_registry.get_op("_contrib_rescale_int8"),
                                "rescaled"))
                q, lo, hi = ins[0][0].inputs
                attrs = {"out_type": node.attrs.get("out_type", "int8")}
                for k in ("min_calib_range", "max_calib_range"):
                    if k in node.attrs:
                        attrs[k] = node.attrs[k]
                mapping[id(node)] = (_Node(
                    op2, f"{prefix}_{node.name}", attrs, [q, lo, hi],
                    arg_names=["qdata", "min_range", "max_range"]), 0)
                changed = True
                continue
            if new is not None:
                dq = _Node(dq_op, f"{node.name}_dequantize", {},
                           [(new, 0), (new, 1), (new, 2)],
                           arg_names=["qdata", "min_range", "max_range"])
                mapping[id(node)] = (dq, 0)
                changed = True

        if not changed:
            return _hoist_requantize(sym)
        sym = S.Symbol(_rebuild_mapped(sym._outputs, mapping))
    return _hoist_requantize(sym)


def _hoist_requantize(sym):
    """Move requantize ABOVE range-preserving int32 ops: relu and
    max-pool are monotone pointwise maps, so
    requantize(act(X)) == act(requantize(X)) — but the left form runs
    act/pool on 4-byte int32 codes while the right runs them on int8 AND
    leaves requantize adjacent to the conv/fc accumulator, where XLA
    fuses it into the conv epilogue (the profiled int8 graph spent 3.3x
    bf16's time in reduce_window_max on int32 codes)."""
    from .. import symbol as S
    from ..symbol.symbol import _Node, _topo
    from ..ops import registry as _registry

    req_op = _registry.get_op("_contrib_requantize")
    q_act = _registry.get_op("_contrib_quantized_act")
    q_pool = _registry.get_op("_contrib_quantized_pooling")

    def hoistable(node):
        return (node.op is q_pool and node.attrs.get("pool_type",
                                                     "max") == "max") \
            or node.op is q_act

    for _ in range(8):
        mapping = {}
        for node in _topo(sym._outputs):
            if node.op is not req_op or id(node) in mapping:
                continue
            if "min_calib_range" not in node.attrs or \
                    "max_calib_range" not in node.attrs:
                # uncalibrated requantize computes its range from the
                # INPUT: hoisting above relu/pool would widen that range
                # to the raw accumulator's negative lobe and coarsen the
                # scale — only the calibrated form commutes exactly
                continue
            p, p_oi = node.inputs[0]
            if p_oi != 0 or not hoistable(p):
                continue
            # requantize consumes (P.q, P.lo, P.hi); P passes lo/hi
            # through, so requantize can read P's own range inputs
            new_req = _Node(req_op, f"hoisted_{node.name}",
                            dict(node.attrs), list(p.inputs),
                            arg_names=list(node.arg_names))
            new_p = _Node(p.op, f"{p.name}_int8", dict(p.attrs),
                          [(new_req, 0), (new_req, 1), (new_req, 2)],
                          arg_names=list(p.arg_names))
            mapping[id(node)] = (new_p, 0)
        if not mapping:
            return sym
        sym = S.Symbol(_rebuild_mapped(sym._outputs, mapping))
    return sym


def fold_batchnorm(sym, arg_params, aux_params):
    """Fold inference-mode BatchNorm into the preceding Convolution
    (reference: the MKLDNN subgraph fuse pass's conv+BN folding) — an
    EXACT transform with running stats:
        W' = W * (gamma / sqrt(var + eps))    (per output channel)
        b' = beta + (b - mean) * gamma / sqrt(var + eps)
    Quantizing the folded conv avoids a separate int8 BN stage and its
    extra requantization error. Returns (sym2, arg2, aux2)."""
    import numpy as _np2
    from .. import symbol as S
    from ..symbol.symbol import _Node, _topo
    from ..ndarray import NDArray
    from ..ndarray import array as _nd_array

    arg2 = dict(arg_params)
    aux2 = dict(aux_params or {})
    order = _topo(sym._outputs)
    consumers = {}
    nonzero_out_use = set()   # node ids consumed at an output index != 0
    for n in order:
        if n.op is None:
            continue
        for (i, oi) in n.inputs:
            consumers.setdefault(id(i), []).append(n)
            if oi != 0:
                nonzero_out_use.add(id(i))
    for n, i in sym._outputs:
        if i != 0:
            nonzero_out_use.add(id(n))

    mapping = {}

    def conv_entry(entry):
        node, idx = entry
        return (mapping[id(node)], idx) if id(node) in mapping else entry

    output_ids = {id(n) for n, _ in sym._outputs}
    folded_weights = set()
    for node in order:
        if node.op is None or node.op.name != "BatchNorm":
            continue
        (src, src_oi) = node.inputs[0]
        if src.op is None or src.op.name != "Convolution" or src_oi != 0:
            continue
        if id(node) in nonzero_out_use:
            continue   # some consumer reads BN output 1/2 (mean/var);
            # the fused conv exposes only output 0, so folding would hand
            # that consumer conv activations — keep the BN
        if len(consumers.get(id(src), [])) != 1 or id(src) in output_ids:
            continue   # conv output used elsewhere / exposed: keep BN
            # (folding mutates the conv WEIGHTS, so every consumer of the
            # raw conv output — including a graph output — must go)
        names = dict(zip(node.arg_names, [i for i, _ in node.inputs]))
        try:
            gamma = arg2[names["gamma"].name].asnumpy()
            beta = arg2[names["beta"].name].asnumpy()
            mean = aux2[names["moving_mean"].name].asnumpy()
            var = aux2[names["moving_var"].name].asnumpy()
        except KeyError:
            continue
        eps = float(node.attrs.get("eps", 1e-3))
        if node.attrs.get("fix_gamma", True) in (True, "True", "true", "1"):
            gamma = _np2.ones_like(gamma)
        scale = gamma / _np2.sqrt(var + eps)

        w_name = None
        b_name = None
        for (inp, _), aname in zip(src.inputs, src.arg_names):
            if aname == "weight":
                w_name = inp.name
            elif aname == "bias":
                b_name = inp.name
        if w_name is None or w_name not in arg2:
            continue
        if w_name in folded_weights:
            continue   # weight shared by another folded conv: a second
            # in-place rescale would compound the scales
        folded_weights.add(w_name)
        w = arg2[w_name].asnumpy()
        b = arg2[b_name].asnumpy() if b_name and b_name in arg2 else \
            _np2.zeros(w.shape[0], w.dtype)
        arg2[w_name] = _nd_array(
            w * scale.reshape((-1,) + (1,) * (w.ndim - 1)))
        nb = beta + (b - mean) * scale
        # the folded conv always carries a bias
        if b_name is None:
            b_name = src.name + "_folded_bias"
        arg2[b_name] = _nd_array(nb.astype(w.dtype))
        new_attrs = dict(src.attrs)
        new_attrs["no_bias"] = False
        bias_var = _Node(None, b_name, {}, [])
        new_inputs = []
        new_argn = []
        has_bias = False
        for (inp, oi), aname in zip(src.inputs, src.arg_names):
            e = conv_entry((inp, oi))
            if aname == "bias":
                new_inputs.append((bias_var, 0))
                has_bias = True
            else:
                new_inputs.append(e)
            new_argn.append(aname)
        if not has_bias:
            new_inputs.append((bias_var, 0))
            new_argn.append("bias")
        fused = _Node(src.op, src.name, new_attrs, new_inputs,
                      extra=dict(src.extra), arg_names=new_argn)
        mapping[id(node)] = fused

    if not mapping:
        return sym, arg2, aux2

    rebuilt = {}

    def rebuild(node):
        """Replace mapped BNs with their fused conv AND rebuild the fused
        node's own input subtree (a fused conv's inputs still reference
        original upstream nodes containing earlier mapped BNs)."""
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        target = mapping.get(id(node), node)
        if target.op is None:
            rebuilt[id(node)] = target
            return target
        new_ins = []
        for inp, oi in target.inputs:
            nb = rebuild(inp)
            # a mapped BatchNorm had 3 outputs; its fused conv exposes 1
            new_ins.append((nb, 0 if id(inp) in mapping else oi))
        nn = _Node(target.op, target.name, target.attrs, new_ins,
                   extra=target.extra, arg_names=target.arg_names)
        rebuilt[id(node)] = nn
        return nn

    new_outputs = []
    for n, i in sym._outputs:
        nb = rebuild(n)
        new_outputs.append((nb, 0 if id(n) in mapping else i))
    return S.Symbol(new_outputs), arg2, aux2


def _calibrate_quantized_sym(sym, calib_data, data_names, num_batches,
                             calib_mode, ctx=None, arg_params=None,
                             aux_params=None):
    """Collect per-layer output ranges from fp32 execution (reference
    quantization.py _collect_layer_statistics / _LayerOutputCollector)."""
    internals = sym.get_internals()
    out_names = internals.list_outputs()
    shapes = {d.name: tuple(d.shape) for d in calib_data.provide_data}
    lbl = {d.name: tuple(d.shape)
           for d in (calib_data.provide_label or [])}
    shapes.update(lbl)
    ex = internals.simple_bind(ctx, grad_req="null", **shapes)
    if arg_params or aux_params:
        ex.copy_params_from(arg_params or {}, aux_params or {},
                            allow_extra_params=True)

    # bounded memory: running min/max for naive; a capped per-layer sample
    # for the entropy KL sweep (the reference keeps per-layer histograms,
    # quantization.py LayerHistogramCollector — a sample bounds host RAM
    # the same way without a two-pass range scan)
    SAMPLE_CAP = 1 << 18
    minmax = {}
    samples = {}
    rng = _np.random.RandomState(0)
    calib_data.reset()
    for nbatch, batch in enumerate(calib_data):
        if nbatch >= num_batches:
            break
        feeds = {n: a for n, a in zip(data_names, batch.data)}
        if batch.label:
            for d, a in zip(calib_data.provide_label, batch.label):
                feeds[d.name] = a
        outs = ex.forward(is_train=False, **feeds)
        for name, arr in zip(out_names, outs):
            v = arr.asnumpy().ravel()
            lo, hi = float(v.min()), float(v.max())
            if name in minmax:
                plo, phi = minmax[name]
                minmax[name] = (min(lo, plo), max(hi, phi))
            else:
                minmax[name] = (lo, hi)
            if calib_mode != "naive":
                if v.size > SAMPLE_CAP // max(1, num_batches):
                    idx = rng.choice(v.size,
                                     SAMPLE_CAP // max(1, num_batches),
                                     replace=False)
                    v = v[idx]
                samples.setdefault(name, []).append(v)

    ranges = {}
    for name, (lo, hi) in minmax.items():
        if calib_mode == "naive":
            ranges[name] = (lo, hi)
        else:  # entropy
            t = _optimal_threshold_kl(_np.concatenate(samples[name]))
            ranges[name] = (-t, t)
    return ranges


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=None, calib_mode="entropy",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=logging):
    """Reference quantization.py quantize_model: returns
    (quantized symbol, quantized arg_params, aux_params)."""
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise MXNetError(f"unsupported quantized_dtype {quantized_dtype!r}")
    if quantized_dtype == "auto":
        quantized_dtype = "int8"
    excluded = list(excluded_sym_names or [])

    calib_ranges = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} requires calib_data")
        batch = calib_data.provide_data[0].shape[0]
        num_batches = max(1, (num_calib_examples or batch) // batch)
        calib_ranges = _calibrate_quantized_sym(
            sym, calib_data, list(data_names), num_batches, calib_mode, ctx,
            arg_params=arg_params, aux_params=aux_params)

    # weight/bias ranges come from the params themselves
    for pname, arr in arg_params.items():
        v = arr.asnumpy()
        calib_ranges[pname] = (float(v.min()), float(v.max()))

    # rewrite: per-node input keys expected as f"{node}_{argname}"
    # translate node input stats: data input of node X is the output of its
    # predecessor — quantize_graph falls back to on-the-fly ranges when a
    # key is missing, so partial coverage is fine.
    from ..symbol.symbol import _topo
    for node in _topo(sym._outputs):
        if node.op is None or node.op.name not in _QUANTIZABLE:
            continue
        for (inp, oi), aname in zip(node.inputs, node.arg_names):
            key = f"{node.name}_{aname}"
            if inp.op is None:
                if inp.name in calib_ranges:
                    calib_ranges[key] = calib_ranges[inp.name]
            else:
                src = f"{inp.name}_output"
                if src in calib_ranges:
                    calib_ranges[key] = calib_ranges[src]

    qsym = quantize_graph(sym, excluded, quantized_dtype, calib_ranges)

    # parameter shapes are no longer inferrable through the quantize nodes
    # (the per-op weight-shape rules attach to the fp32 ops); hint them on
    # the variable nodes so simple_bind works from data shapes alone
    from ..symbol.symbol import _topo as _topo2
    for node in _topo2(qsym._outputs):
        if node.op is None and node.name in arg_params:
            node.extra.setdefault("__shape__",
                                  tuple(arg_params[node.name].shape))

    # OFFLINE weight quantization (reference quantize_graph_pass.cc
    # OfflineParams + quantization.py _quantize_params): every
    # quantize_v2 whose input is a parameter variable is evaluated NOW
    # and replaced by stored int8 codes + range scalars. Without this the
    # fp32 weights are re-read and re-quantized on EVERY inference step —
    # measured as the dominant extra HBM traffic of the int8 graph.
    qsym, qparams, consumed = _offline_quantize_params(qsym, arg_params)
    out_args = {k: v for k, v in arg_params.items() if k not in consumed}
    out_args.update(qparams)
    return qsym, out_args, dict(aux_params or {})


def _offline_quantize_params(sym, arg_params):
    """Fold param-input quantize_v2 nodes into stored int8 arrays.
    Returns (new_sym, {new_param_name: NDArray}, {consumed fp32 names});
    a consumed fp32 param is dropped unless something else still
    references it."""
    from .. import symbol as S
    from ..symbol.symbol import _Node, _topo
    from ..ops import registry as _registry
    from ..ndarray import array as _nd_array

    import numpy as _np2

    q_v2 = _registry.get_op("_contrib_quantize_v2")
    new_params = {}
    repl = {}        # id(quantize_node) -> [qvar, lovar, hivar]
    consumed = {}    # fp32 name -> count of folded consumers

    for node in _topo(sym._outputs):
        if node.op is not q_v2 or not node.inputs:
            continue
        inp, oi = node.inputs[0]
        if inp.op is not None or inp.name not in arg_params or oi != 0:
            continue
        w = arg_params[inp.name].asnumpy()
        kw = {"out_type": node.attrs.get("out_type", "int8")}
        for k in ("min_calib_range", "max_calib_range"):
            if k in node.attrs:
                kw[k] = float(node.attrs[k])
        import jax.numpy as _jnp
        q, mn, mx = q_v2.fn(_jnp.asarray(w), **kw)
        names = [f"{node.name}_weight", f"{node.name}_min",
                 f"{node.name}_max"]
        vars_ = []
        for nm, val in zip(names, (q, mn, mx)):
            val = _np2.asarray(val)
            new_params[nm] = _nd_array(val)
            v = _Node(None, nm, {}, [])
            v.extra["__shape__"] = tuple(val.shape)
            # without the dtype hint simple_bind allocates f32 arrays for
            # the int8 codes and copy_params_from casts them — the
            # quantized ops then mis-scale on the real chip
            v.extra["__dtype__"] = str(val.dtype)
            vars_.append(v)
        repl[id(node)] = vars_
        consumed[inp.name] = True

    if not repl:
        return sym, {}, set()

    rebuilt = {}
    still_referenced = set()

    def rebuild(node):
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        if node.op is None:
            still_referenced.add(node.name)
            rebuilt[id(node)] = node
            return node
        new_ins = []
        for inp, oi in node.inputs:
            if id(inp) in repl:
                new_ins.append((repl[id(inp)][oi], 0))
            else:
                new_ins.append((rebuild(inp), oi))
        nn = _Node(node.op, node.name, node.attrs, new_ins,
                   extra=node.extra, arg_names=node.arg_names)
        rebuilt[id(node)] = nn
        return nn

    outs = []
    for n, i in sym._outputs:
        if id(n) in repl:
            outs.append((repl[id(n)][i], 0))
        else:
            outs.append((rebuild(n), i))
    drop = {n for n in consumed if n not in still_referenced}
    return S.Symbol(outs), new_params, drop
